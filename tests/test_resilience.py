"""Chaos suite: injected faults must recover bit-exact or fail classified.

The acceptance contract of the resilience layer (ISSUE 2): under injected
OOM / corrupt-cache / truncated-trace / killed-worker faults, runs either
recover to results BIT-IDENTICAL to a clean run (the degradation ladder's
rungs are all result-invariant knobs) or fail with a classified
``PlussError`` naming the site — no raw XLA/OS exception escapes a
resilient entry point, and an interrupted ``sweep --resume`` recomputes
zero finished points.
"""

import json
import os

import numpy as np
import pytest

from pluss import engine, trace
from pluss.config import SamplerConfig
from pluss.models import gemm
from pluss.resilience import (
    CacheCorrupt,
    CollectiveError,
    CompileError,
    DataLoss,
    FaultPlan,
    Journal,
    PlussError,
    ResourceExhausted,
    ShareCapOverflow,
    classify,
    run_resilient,
    replay_file_resilient,
)
from pluss.resilience import faults
from pluss.resilience.ladder import LADDER, Retry

CFG = SamplerConfig(cls=8)


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    """No test may leak an installed fault plan into the next."""
    faults.install(None)
    yield
    faults.install(None)


@pytest.fixture()
def fast_retry():
    return Retry(backoff_s=0.0)


# ---------------------------------------------------------------------------
# error taxonomy


def test_classify_oom_markers():
    for msg in ("RESOURCE_EXHAUSTED: Out of memory allocating 2.5G",
                "XlaRuntimeError: RESOURCE_EXHAUSTED while running"):
        e = classify(RuntimeError(msg), site="engine.run")
        assert isinstance(e, ResourceExhausted)
        assert e.degradable and not e.retryable and not e.fatal
        assert e.site == "engine.run"
        assert e.__cause__ is e.cause


def test_classify_engine_budget_guard_is_degradable():
    # the plan-time sort-budget guard IS an OOM prediction — same rung
    e = classify(RuntimeError(
        "nest 0: the sort window stream needs ~12.00 GiB ... beyond the "
        "8.00 GiB device budget."))
    assert isinstance(e, ResourceExhausted)


def test_classify_share_cap_carries_needed():
    e = classify(engine.ShareCapExceeded(4096, 1024))
    assert isinstance(e, ShareCapOverflow)
    assert e.retryable and e.needed == 4096


def test_classify_compile_collective_memory_unknown():
    assert isinstance(classify(RuntimeError("XLA compilation failed")),
                      CompileError)
    assert isinstance(classify(ConnectionError("refused")), CollectiveError)
    assert isinstance(classify(MemoryError()), ResourceExhausted)
    unk = classify(ValueError("no marker at all"), site="s")
    assert type(unk) is PlussError and unk.fatal


def test_classify_idempotent_on_pluss_errors():
    e = DataLoss("gone", site="trace.load")
    assert classify(e) is e
    assert isinstance(CacheCorrupt("x"), PlussError)


# ---------------------------------------------------------------------------
# fault injector


def test_fault_plan_grammar():
    plan = FaultPlan.parse("oom, oom@2 ,corrupt_cache,kill_worker@1")
    assert plan.describe() == "oom@1,oom@2,corrupt_cache@1,kill_worker@1"
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse("frobnicate")
    with pytest.raises(ValueError, match="occurrence"):
        FaultPlan.parse("oom@x")


def test_fault_plan_random_is_seed_deterministic():
    a, b = FaultPlan.random(7, 3), FaultPlan.random(7, 3)
    assert a.describe() == b.describe()
    assert a.describe() != FaultPlan.random(8, 3).describe()


def test_fault_fires_at_exact_occurrence():
    plan = FaultPlan.parse("oom@2")
    faults.install(plan)
    faults.check("engine.run")            # hit 1: clean
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        faults.check("engine.run")        # hit 2: armed
    faults.check("engine.run")            # hit 3: entry spent


# ---------------------------------------------------------------------------
# degradation ladder


def test_injected_oom_walks_ladder_bit_exact(fast_retry):
    clean = engine.run(gemm(16), CFG)
    faults.install(FaultPlan.parse("oom,oom@2"))
    res = run_resilient(gemm(16), CFG, retry=fast_retry)
    assert res.degradations == ("shrink_window", "raise_n_windows")
    assert res.noshare_dense.tolist() == clean.noshare_dense.tolist()
    assert res.share_raw == clean.share_raw
    assert res.max_iteration_count == clean.max_iteration_count


def test_injected_oom_reaches_sliced_pipeline_bit_exact(fast_retry):
    clean = engine.run(gemm(16), CFG)
    faults.install(FaultPlan.parse("oom,oom@2,oom@3"))
    res = run_resilient(gemm(16), CFG, retry=fast_retry)
    assert res.degradations == LADDER[:3]
    assert res.noshare_dense.tolist() == clean.noshare_dense.tolist()
    assert res.share_raw == clean.share_raw


def test_shard_backend_ladder_degrades_to_single_device(fast_retry):
    from tests.conftest import require_shard_backend

    require_shard_backend()
    clean = engine.run(gemm(16), CFG)
    # two shard-entry OOMs walk shrink_window then single_device (the
    # windowed engine is the same computation — backend equivalence)
    faults.install(FaultPlan.parse("shard_oom,shard_oom@2"))
    res = run_resilient(gemm(16), CFG, backend="shard", retry=fast_retry)
    assert res.degradations == ("shrink_window", "single_device")
    assert res.noshare_dense.tolist() == clean.noshare_dense.tolist()
    assert res.share_raw == clean.share_raw


def test_injected_compile_failure_degrades(fast_retry):
    clean = engine.run(gemm(16), CFG)
    faults.install(FaultPlan.parse("compile"))
    res = run_resilient(gemm(16), CFG, retry=fast_retry)
    assert res.degradations == ("shrink_window",)
    assert res.noshare_dense.tolist() == clean.noshare_dense.tolist()


def test_share_cap_injection_folds_into_auto_retry(fast_retry, capsys):
    # injected at engine.finalize: the engine's own auto-retry machinery
    # absorbs it (no ladder rung consumed), result identical
    clean = engine.run(gemm(16), CFG)
    faults.install(FaultPlan.parse("share_cap"))
    res = run_resilient(gemm(16), CFG, retry=fast_retry)
    assert res.degradations == ()
    assert res.noshare_dense.tolist() == clean.noshare_dense.tolist()
    assert res.share_raw == clean.share_raw


def test_exhausted_ladder_raises_classified_not_raw(fast_retry):
    # more OOMs than rungs: the final failure must surface AS the taxonomy
    faults.install(FaultPlan.parse("oom,oom@2,oom@3,oom@4,oom@5"))
    with pytest.raises(ResourceExhausted):
        run_resilient(gemm(16), CFG, retry=fast_retry)


def test_plain_engine_run_still_raises_raw():
    # the UNwrapped entry point keeps raw semantics — resilience is the
    # executor's job, not a silent behavior change under everyone
    faults.install(FaultPlan.parse("oom"))
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        engine.run(gemm(16), CFG)


def test_describe_path_carries_degradation_stamp():
    label = engine.describe_path(gemm(16), CFG,
                                 degradations=("shrink_window",
                                               "cpu_fallback"))
    assert label.endswith("[degraded: shrink_window,cpu_fallback]")
    assert engine.describe_path(gemm(16), CFG) == label.split(" [")[0]


# ---------------------------------------------------------------------------
# plan cache quarantine


@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("PLUSS_NO_PLAN_CACHE", raising=False)
    monkeypatch.setenv("PLUSS_PLAN_CACHE_DIR", str(tmp_path))
    engine.compiled.cache_clear()
    yield tmp_path
    engine.compiled.cache_clear()


def test_corrupt_plan_cache_entry_quarantined(cache_dir, capsys):
    clean = engine.run(gemm(16), CFG)
    entries = [f for f in os.listdir(cache_dir) if f.endswith(".pkl")]
    assert entries, "plan cache should have been populated"
    path = cache_dir / entries[0]
    with open(path, "r+b") as f:
        f.write(b"\x00GARBAGE")
    engine.compiled.cache_clear()
    res = engine.run(gemm(16), CFG)
    assert res.noshare_dense.tolist() == clean.noshare_dense.tolist()
    corrupt = [f for f in os.listdir(cache_dir) if f.endswith(".corrupt")]
    assert corrupt == [entries[0] + ".corrupt"]
    # the rebuilt artifact landed back in the now-free slot
    assert entries[0] in os.listdir(cache_dir)
    assert "quarantined" in capsys.readouterr().err


def test_fault_injected_cache_corruption_recovers(cache_dir):
    clean = engine.run(gemm(16), CFG)
    engine.compiled.cache_clear()
    faults.install(FaultPlan.parse("corrupt_cache"))
    res = engine.run(gemm(16), CFG)
    assert res.noshare_dense.tolist() == clean.noshare_dense.tolist()
    assert any(f.endswith(".corrupt") for f in os.listdir(cache_dir))


def test_plan_cache_tmp_names_are_unique():
    import re

    src = open(os.path.join(os.path.dirname(engine.__file__),
                            "engine.py")).read()
    # the tmp slot must be unique beyond the pid (threads share a pid)
    assert re.search(r"\.tmp\.\{os\.getpid\(\)\}\.\{uuid", src)


# ---------------------------------------------------------------------------
# trace I/O hardening + checkpointed staging/replay


def test_truncated_u64_trace_rejected(tmp_path):
    p = tmp_path / "bad.bin"
    p.write_bytes(b"\x01" * 17)
    with pytest.raises(DataLoss, match=r"17 bytes.*offset 16"):
        trace.load_trace(str(p))
    with pytest.raises(DataLoss):
        trace.replay_file(str(p))
    with pytest.raises(DataLoss):
        trace.pack_file(str(p), str(tmp_path / "out.pack"))


def test_garbage_text_trace_line_rejected(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("0x40\n128\nnot hex\n")
    with pytest.raises(DataLoss, match="line 3"):
        trace.load_trace(str(p), "text")


def _mk_trace(tmp_path, n=5 * 8 * 512 + 77, seed=0):
    rng = np.random.default_rng(seed)
    p = tmp_path / "t.bin"
    (rng.integers(0, 1 << 12, n, dtype=np.int64) << 6).astype(
        "<u8").tofile(p)
    return str(p), n


def test_replay_checkpoint_resume_bit_exact(tmp_path):
    # batch_windows pinned to 8: the fault-hit arithmetic below assumes
    # 6 batches of 8*512 refs (the pre-round-6 default batching)
    tf, _ = _mk_trace(tmp_path)
    W = 512
    clean = trace.replay_file(tf, window=W)
    ck = str(tmp_path / "t.ckpt.npz")
    faults.install(FaultPlan.parse("trace_loss@4"))
    with pytest.raises(DataLoss):
        trace.replay_file(tf, window=W, batch_windows=8,
                          checkpoint_path=ck,
                          checkpoint_every=1, resume=True)
    faults.install(None)
    assert os.path.exists(ck)
    res = trace.replay_file(tf, window=W, batch_windows=8,
                            checkpoint_path=ck,
                            checkpoint_every=1, resume=True)
    assert res.hist.tolist() == clean.hist.tolist()
    assert res.total_count == clean.total_count
    assert not os.path.exists(ck), "finished run must retire its checkpoint"


def test_replay_checkpoint_corrupt_quarantined(tmp_path, capsys):
    tf, _ = _mk_trace(tmp_path)
    ck = tmp_path / "t.ckpt.npz"
    ck.write_bytes(b"not an npz at all")
    clean = trace.replay_file(tf, window=512)
    res = trace.replay_file(tf, window=512, checkpoint_path=str(ck),
                            resume=True)
    assert res.hist.tolist() == clean.hist.tolist()
    assert os.path.exists(str(ck) + ".corrupt")
    assert "quarantined" in capsys.readouterr().err


def test_replay_checkpoint_shape_mismatch_starts_fresh(tmp_path, capsys):
    tf, _ = _mk_trace(tmp_path)
    ck = str(tmp_path / "t.ckpt.npz")
    faults.install(FaultPlan.parse("trace_loss@2"))
    with pytest.raises(DataLoss):
        trace.replay_file(tf, window=512, batch_windows=8,
                          checkpoint_path=ck,
                          checkpoint_every=1, resume=True)
    faults.install(None)
    # different window shape: the checkpoint must be ignored, not mixed in
    clean = trace.replay_file(tf, window=256)
    res = trace.replay_file(tf, window=256, batch_windows=8,
                            checkpoint_path=ck,
                            checkpoint_every=1, resume=True)
    assert res.hist.tolist() == clean.hist.tolist()
    assert "different run" in capsys.readouterr().err


def test_pack_file_resume_byte_identical(tmp_path):
    tf, _ = _mk_trace(tmp_path)
    W = 512
    meta_clean = trace.pack_file(tf, str(tmp_path / "clean.pack"), window=W)
    crash = str(tmp_path / "crash.pack")
    faults.install(FaultPlan.parse("trace_loss@3"))
    with pytest.raises(DataLoss):
        trace.pack_file(tf, crash, window=W)
    faults.install(None)
    assert os.path.exists(crash + ".journal")
    meta = trace.pack_file(tf, crash, window=W, resume=True)
    assert meta == meta_clean
    assert (tmp_path / "clean.pack").read_bytes() == \
        open(crash, "rb").read()
    assert not os.path.exists(crash + ".journal"), "spent journal retires"


def test_pack_file_resume_walks_back_past_missing_bytes(tmp_path):
    # power-loss shape: a journal line can outlive the data it promises
    # (data flushed but not durable) — resume must walk BACK to the last
    # batch whose bytes exist, never truncate forward (zero-extension)
    tf, _ = _mk_trace(tmp_path)
    W = 512
    # batch_windows pinned to 8: the journal-batch arithmetic below
    # assumes 6 batches of 8*512 refs (the pre-round-6 default batching)
    trace.pack_file(tf, str(tmp_path / "clean.pack"), window=W)
    crash = str(tmp_path / "y.pack")
    faults.install(FaultPlan.parse("trace_loss@4"))
    with pytest.raises(DataLoss):
        trace.pack_file(tf, crash, window=W, batch_windows=8)
    faults.install(None)
    j = Journal(crash + ".journal")
    b1 = j.get({"batch": 1})["out_bytes"]
    b2 = j.get({"batch": 2})["out_bytes"]
    with open(crash + ".tmp", "r+b") as f:
        f.truncate((b1 + b2) // 2)   # batch 2's tail bytes "lost"
    meta = trace.pack_file(tf, crash, window=W, resume=True,
                           batch_windows=8)
    assert (tmp_path / "clean.pack").read_bytes() == \
        open(crash, "rb").read()
    assert meta["n_lines"] > 0


def test_pack_file_fresh_start_clears_stale_journal(tmp_path):
    # regression: a FRESH pack must not leave an earlier crashed run's
    # high-batch journal records behind — a later resume's contiguity
    # scan would splice them onto the new prefix and truncate() past EOF
    tf, _ = _mk_trace(tmp_path)
    W = 512
    trace.pack_file(tf, str(tmp_path / "clean.pack"), window=W)
    clean_bytes = (tmp_path / "clean.pack").read_bytes()
    crash = str(tmp_path / "x.pack")
    # batch_windows pinned to 8: the fault hits below assume 6 batches
    faults.install(FaultPlan.parse("trace_loss@5"))   # run A: crash late
    with pytest.raises(DataLoss):
        trace.pack_file(tf, crash, window=W, batch_windows=8)
    faults.install(None)
    os.unlink(crash + ".tmp")      # A's partial output is lost entirely
    faults.install(FaultPlan.parse("trace_loss@2"))   # run B: fresh, early
    with pytest.raises(DataLoss):
        trace.pack_file(tf, crash, window=W, batch_windows=8)
    faults.install(None)
    meta = trace.pack_file(tf, crash, window=W, resume=True,
                           batch_windows=8)
    assert open(crash, "rb").read() == clean_bytes
    assert meta["n_lines"] > 0


def test_replay_resilient_classifies_data_loss(tmp_path):
    tf, _ = _mk_trace(tmp_path)
    faults.install(FaultPlan.parse("trace_loss"))
    with pytest.raises(DataLoss):
        replay_file_resilient(tf, window=512, retry=Retry(backoff_s=0))


def test_replay_resilient_serial_feed_rung(tmp_path, monkeypatch):
    """The trace ladder's FIRST rung drops the parallel pool + compressed
    wire back to the single reader + fixed-width pack — and only that:
    the window is untouched, and the degraded result is bit-identical."""
    from pluss.resilience.errors import ResourceExhausted

    tf, _ = _mk_trace(tmp_path)
    ref = trace.replay_file(tf, window=512)
    real = trace.replay_file
    calls = []

    def flaky(path, fmt="u64", **kw):
        calls.append(kw)
        if len(calls) == 1:
            # a degradable failure on the pooled/compressed attempt (the
            # shape an overdeep in-flight pipeline would OOM with)
            raise ResourceExhausted("synthetic", site="trace.replay")
        return real(path, fmt, **kw)

    monkeypatch.setattr(trace, "replay_file", flaky)
    res = replay_file_resilient(tf, window=512, wire="d24v",
                                feed_workers=3, retry=Retry(backoff_s=0))
    assert res.degradations == ("serial_feed",)
    assert calls[0]["feed_workers"] == 3 and calls[0]["wire"] == "d24v"
    assert calls[1]["feed_workers"] == 1 and calls[1]["wire"] == "pack"
    assert calls[1]["window"] == 512          # rung sheds the feed ONLY
    # the result records the feed the SUCCESSFUL attempt ran (what bench
    # stamps on the metric line), not the pre-degradation request
    assert res.wire == "pack" and res.feed_workers == 1
    np.testing.assert_array_equal(res.hist, ref.hist)

    # CHECKPOINTED runs keep their wire across the rung (it is part of
    # the checkpoint identity — flipping it would discard the durable
    # prefix as a "different run"), and an unset wire is pinned to its
    # auto-resolution up-front for the same reason
    calls.clear()
    ck = str(tmp_path / "rung.ckpt.npz")
    res = replay_file_resilient(tf, window=512, wire="d24v",
                                feed_workers=3, checkpoint_path=ck,
                                retry=Retry(backoff_s=0))
    assert res.degradations == ("serial_feed",)
    assert calls[1]["feed_workers"] == 1 and calls[1]["wire"] == "d24v"
    np.testing.assert_array_equal(res.hist, ref.hist)
    calls.clear()
    res = replay_file_resilient(tf, window=512, checkpoint_path=ck,
                                wire="auto", retry=Retry(backoff_s=0))
    # an unset OR explicit-`auto` wire is pinned to its resolution
    # up-front — `auto` must not re-resolve differently mid-run
    assert calls[0]["wire"] == trace._resolve_wire("auto")
    np.testing.assert_array_equal(res.hist, ref.hist)


def test_replay_resilient_passes_batching_knobs_through(tmp_path):
    """The ladder wrapper forwards the round-6 feed knobs (batch_windows,
    queue_depth, segmented) untouched, and deadline truncation under the
    wrapper still cuts exactly on the configured batch boundary."""
    tf, _ = _mk_trace(tmp_path)
    ref = trace.replay_file(tf, window=512)
    res = replay_file_resilient(tf, window=512, batch_windows=3,
                                queue_depth=1, segmented=False,
                                retry=Retry(backoff_s=0))
    assert res.degradations == ()
    np.testing.assert_array_equal(res.hist, ref.hist)
    cut = replay_file_resilient(tf, window=512, batch_windows=2,
                                deadline_s=0.0, retry=Retry(backoff_s=0))
    assert 0 < cut.total_count <= ref.total_count
    assert cut.total_count % (2 * 512) == 0


# ---------------------------------------------------------------------------
# journal + sweep resume


def test_journal_atomic_records_and_torn_tail(tmp_path, capsys):
    jp = tmp_path / "j.jsonl"
    j = Journal(str(jp))
    j.record({"a": 1}, x=2)
    j.record({"a": 2}, x=3)
    with open(jp, "a") as f:
        f.write('{"key": {"a": 3}, "x":')   # torn final line (crash)
    j2 = Journal(str(jp))
    assert len(j2) == 2 and j2.get({"a": 2})["x"] == 3
    assert "torn final line" in capsys.readouterr().err
    # corruption in the MIDDLE is not a crash artifact: classified as a
    # RETRYABLE CacheCorrupt (the journal is a rebuildable artifact —
    # delete and recompute — unlike a truncated source trace)
    lines = jp.read_text().splitlines()
    lines[0] = "garbage"
    jp.write_text("\n".join(lines) + "\n")
    with pytest.raises(CacheCorrupt, match="line 1") as ei:
        Journal(str(jp))
    assert ei.value.retryable and not ei.value.fatal


def test_interrupted_sweep_resumes_without_recompute(tmp_path, monkeypatch):
    from pluss import sweep as sweep_mod

    jp = str(tmp_path / "sweep.jsonl")
    pts = sweep_mod.sweep(gemm(16), (1, 2), (2,), CFG, journal=jp)
    # poison the engine: a resumed sweep that recomputes ANY finished
    # point fails loudly
    def boom(*a, **k):
        raise AssertionError("recomputed a finished sweep point")
    monkeypatch.setattr(engine, "run", boom)
    monkeypatch.setattr(engine, "run_sliced", boom)
    pts2 = sweep_mod.sweep(gemm(16), (1, 2), (2,), CFG, journal=jp,
                           resume=True)
    for p, q in zip(pts, pts2):
        assert np.array_equal(p.curve, q.curve)
        assert p.total_refs == q.total_refs
        assert q.degradations[0] == "journal"


def test_partially_journaled_sweep_computes_only_missing(tmp_path):
    from pluss import sweep as sweep_mod

    jp = str(tmp_path / "sweep.jsonl")
    sweep_mod.sweep(gemm(16), (1,), (2,), CFG, journal=jp)
    calls = []
    real = engine.run

    def counting(*a, **k):
        calls.append(a)
        return real(*a, **k)

    engine.run = counting
    try:
        pts = sweep_mod.sweep(gemm(16), (1, 2), (2,), CFG, journal=jp,
                              resume=True)
    finally:
        engine.run = real
    assert len(calls) == 1, "only the missing (t=2) point may run"
    direct = sweep_mod.sweep(gemm(16), (1, 2), (2,), CFG)
    for p, q in zip(pts, direct):
        assert np.array_equal(p.curve, q.curve)


def test_cli_sweep_resume_flag(tmp_path, monkeypatch, capsys):
    from pluss import cli

    monkeypatch.chdir(tmp_path)
    args = ["sweep", "--n", "16", "--cpu", "--sweep-threads", "1",
            "--sweep-chunks", "4", "--cache-lines", "64", "--resume"]
    cli.main(args)
    first = capsys.readouterr()
    assert os.path.exists(".pluss_sweep_gemm_16.jsonl")
    cli.main(args)
    second = capsys.readouterr()
    # resumed rows restore from the journal (stamped in the table)
    assert "journal" in second.out
    assert "mr@64" in first.out and "mr@64" in second.out


# ---------------------------------------------------------------------------
# multihost: liveness + bring-up backoff (fast, single-process units; the
# 2-process kill test lives below, marked slow like its harness sibling)


def test_heartbeat_and_dead_worker_detection(tmp_path):
    import time

    from pluss.parallel import multihost

    hb = str(tmp_path / "hb")
    stop0 = multihost.start_heartbeat(hb, 0, interval_s=0.05)
    stop1 = multihost.start_heartbeat(hb, 1, interval_s=0.05)
    try:
        deadline = time.time() + 5
        while multihost.dead_workers(hb, 2, stale_s=10) and \
                time.time() < deadline:
            time.sleep(0.05)
        assert multihost.dead_workers(hb, 2, stale_s=10) == []
        stop1()   # "kill" worker 1
        time.sleep(0.6)
        assert multihost.dead_workers(hb, 2, stale_s=0.5) == [1]
    finally:
        stop0()
        stop1()


def test_initialize_retries_with_backoff(monkeypatch):
    import jax

    from pluss.parallel import multihost

    calls = []

    def flaky(**kw):
        calls.append(kw)
        if len(calls) < 3:
            raise ConnectionError("refused (synthetic)")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    multihost.initialize(coordinator_address="x:1", num_processes=2,
                         process_id=0, max_retries=3, backoff_s=0.0)
    assert len(calls) == 3

    calls.clear()

    def always(**kw):
        calls.append(kw)
        raise ConnectionError("refused")

    monkeypatch.setattr(jax.distributed, "initialize", always)
    with pytest.raises(CollectiveError, match="after 2 attempts"):
        multihost.initialize(max_retries=2, backoff_s=0.0)


def test_injected_collective_fault_then_recovery(monkeypatch):
    import jax

    from pluss.parallel import multihost

    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: None)
    faults.install(FaultPlan.parse("collective"))
    # one injected connect failure, absorbed by the retry loop
    multihost.initialize(max_retries=2, backoff_s=0.0)


# ---------------------------------------------------------------------------
# report surfaces


def test_bench_emit_carries_degradations(capsys):
    import bench

    bench.emit("m", 100, 2.0, None, path="template",
               degradations=("shrink_window",))
    out = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(out)
    assert rec["degradations"] == ["shrink_window"]
    bench.emit("m2", 100, 2.0, None)
    rec2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec2["degradations"] == []


def test_readme_failure_model_is_synced():
    """README 'Failure model & recovery' must name every error class, every
    ladder rung, every fault kind, and the --resume surface (the same
    test-synced contract as the PLxxx code table)."""
    from pluss.resilience import errors
    from pluss.resilience.faults import KIND_SITE
    from pluss.resilience.ladder import LADDER, SHARD_LADDER, TRACE_LADDER

    readme = open(os.path.join(os.path.dirname(__file__), "..",
                               "README.md")).read()
    start = readme.index("## Failure model & recovery")
    section = readme[start:]
    from pluss.resilience.ladder import SERVE_LADDER

    for cls_ in (errors.PlussError, errors.ResourceExhausted,
                 errors.CompileError, errors.ShareCapOverflow,
                 errors.CollectiveError, errors.WorkerDied,
                 errors.DataLoss, errors.CacheCorrupt,
                 errors.Overloaded, errors.DeadlineExceeded,
                 errors.InvalidRequest):
        assert cls_.__name__ in section, f"missing {cls_.__name__}"
    assert "SERVE_LADDER" in section, \
        "the serve rung subset must be documented with the ladders"
    for rung in set(LADDER) | set(SHARD_LADDER) | set(TRACE_LADDER) \
            | set(SERVE_LADDER):
        assert rung in section, f"missing ladder rung {rung}"
    for kind in KIND_SITE:
        assert kind in section, f"missing fault kind {kind}"
    assert "--resume" in section
    assert "PLUSS_FAULT_PLAN" in section


# ---------------------------------------------------------------------------
# killed worker in the 2-process harness (slow, like test_multihost.py):
# the coordinator must DETECT the death within the watchdog timeout and
# salvage a bit-exact result on its local devices.

WORKER = r"""
import json, os, sys, time
from pluss.utils.platform import force_cpu
force_cpu(4)
from pluss.parallel import multihost

port, pid, out_path, hb_dir = (sys.argv[1], int(sys.argv[2]), sys.argv[3],
                               sys.argv[4])
multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=2, process_id=pid)

from pluss.config import SamplerConfig
from pluss.models import gemm
# backend bring-up (a cross-process exchange) happens BEFORE the chaos
# window opens: the fault models a worker dying MID-RUN, the scenario the
# watchdog owns — a death during bring-up is initialize()'s timeout story
mesh = multihost.global_mesh()
stop = multihost.start_heartbeat(hb_dir, pid, interval_s=0.2)
t0 = time.time()
res = multihost.watched_shard_run(
    gemm(16), SamplerConfig(cls=8), mesh=mesh, hb_dir=hb_dir,
    num_processes=2, timeout_s=90, stale_s=3.0, first_beat_timeout_s=30,
    window_accesses=1)
if multihost.is_coordinator():
    with open(out_path + ".tmp", "w") as f:
        json.dump({
            "detect_s": time.time() - t0,
            "degradations": list(res.degradations),
            "count": res.max_iteration_count,
            "hist": res.noshare_dense.tolist(),
            "share": [{str(k): v for k, v in d.items()}
                      for d in res.share_raw],
        }, f)
    os.replace(out_path + ".tmp", out_path)
stop()
# skip interpreter-exit cleanup: the distributed client's atexit shutdown
# barriers against the chaos-killed peer (hang, then SIGABRT from the
# coordination service) — the salvage result is already durable above
os._exit(0)
"""


@pytest.mark.slow
def test_killed_worker_detected_and_salvaged(tmp_path):
    import socket
    import subprocess
    import sys as _sys

    port = socket.socket()
    port.bind(("127.0.0.1", 0))
    portno = port.getsockname()[1]
    port.close()

    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    out = tmp_path / "res.json"
    hb_dir = tmp_path / "hb"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env = {**os.environ, "JAX_PLATFORMS": "cpu", "JAX_ENABLE_X64": "1",
                "PYTHONPATH": repo + os.pathsep
                + os.environ.get("PYTHONPATH", "")}
    base_env.pop("XLA_FLAGS", None)
    logs = [tmp_path / f"worker{i}.log" for i in range(2)]
    handles: list = []
    procs: list = []
    try:
        for i in range(2):
            env = dict(base_env)
            if i == 1:
                # the chaos plan: worker 1 hard-exits from its heartbeat
                # thread right after its first beat (SIGKILL-equivalent)
                env["PLUSS_FAULT_PLAN"] = "kill_worker@1"
            handles.append(open(logs[i], "w"))
            procs.append(subprocess.Popen(
                [_sys.executable, str(script), str(portno), str(i),
                 str(out), str(hb_dir)],
                env=env, stdout=handles[i], stderr=subprocess.STDOUT,
            ))
        procs[0].wait(timeout=600)
        assert procs[0].returncode == 0, \
            f"coordinator failed:\n{logs[0].read_text()[-3000:]}"
        procs[1].wait(timeout=60)
        assert procs[1].returncode == 43, \
            f"worker 1 should have been chaos-killed (rc=43), got " \
            f"{procs[1].returncode}:\n{logs[1].read_text()[-2000:]}"
    finally:
        try:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    try:
                        p.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        pass
        finally:
            for h in handles:
                h.close()
    got = json.load(open(out))
    assert got["detect_s"] < 120, "death must be detected within the timeout"
    assert got["degradations"][-1] == "local_salvage"
    assert got["degradations"][0].startswith("worker_died")

    ref = engine.run(gemm(16), SamplerConfig(cls=8))
    assert got["count"] == ref.max_iteration_count
    assert got["hist"] == ref.noshare_dense.tolist()
    assert got["share"] == [
        {str(k): v for k, v in d.items()} for d in ref.share_raw
    ]
