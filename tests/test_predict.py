"""predict ≡ engine: the sampling-free symbolic MRC path (round r12).

The contract under test (`pluss/analysis/ri.py` + `pluss/analysis/
polycount.py`):

- **Exactness**: on every derivable spec the symbolic per-thread
  histograms are BIT-IDENTICAL to a real `engine.run` — same noshare
  bins, same share raw keys, same masses, same access count
  (`Prediction.matches_engine`).  The composed MRC is bit-identical on
  the closed-form (uniform-reuse) families and within `ri.MRC_EPS`
  elsewhere (bit-equal histograms can still differ by float summation
  ORDER inside CRI's dilation — the engine's share_raw dict carries
  device-merge insertion order, the symbolic one is sorted).
- **Soundness**: the exact plateau (`mrc.plateau_of`) must lie inside
  PR-3's heuristic MrcBracket `[c_lo, c_hi]` on every derivable spec —
  a violation means one of the two independent provers is wrong (PL704).
- **Zero device dispatches**: the whole predict path is host arithmetic;
  `engine.DEVICE_DISPATCHES` is the witness.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from pluss import cli, cri, engine, mrc, sweep
from pluss.analysis import ri, sarif
from pluss.analysis.diagnostics import CODES, Diagnostic, Severity
from pluss.config import SamplerConfig
from pluss.models import REGISTRY

# the fast tier-1 subset: both closed-form rungs (gemm: G=1 rectangular;
# conv2d: multi-coefficient uniform) and three dense-rung shapes
# (triangular lu, rectangular-multi-nest atax, self-reuse syrk)
FAST_FAMILIES = ("gemm", "conv2d", "lu", "syrk", "atax")
#: families the closed-form periodic rung must take at the default config
CLOSED_FORM = {"gemm", "conv2d"}


def _engine_curve(res, cfg):
    return mrc.aet_mrc(cri.distribute(res.noshare_list(), res.share_list(),
                                      cfg.thread_num), cfg)


# ---------------------------------------------------------------------------
# predict ≡ engine (fast tier-1 subset)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", FAST_FAMILIES)
def test_fast_predict_matches_engine(name):
    spec = REGISTRY[name](16)
    cfg = SamplerConfig(thread_num=4, chunk_size=4)
    rep = ri.predict(spec, cfg)
    assert rep.prediction.derivable, rep.prediction.diagnostics
    if name in CLOSED_FORM:
        assert rep.prediction.method == "closed-form"
    res = engine.run(spec, cfg)
    # histograms bit-identical — bins, raw share keys, masses, accesses
    assert rep.prediction.matches_engine(res)
    theirs = _engine_curve(res, cfg)
    assert len(rep.curve) == len(theirs)
    if name in CLOSED_FORM:
        # uniform families: the composed curve is bit-identical too
        assert np.array_equal(np.asarray(rep.curve), np.asarray(theirs))
    err = float(np.max(np.abs(np.asarray(rep.curve) - np.asarray(theirs))))
    assert err <= ri.MRC_EPS
    ok, detail = ri.check_against_engine(rep, res, cfg)
    assert ok, detail
    assert detail["histogram_identical"] and detail["plateau_in_bracket"]


def test_predict_matches_engine_across_threads():
    # the thread axis is where the closed-form period shift lives: the
    # same family must stay bit-exact at T=1 (no sharing at all) and T=2
    spec_builder = REGISTRY["gemm"]
    for T in (1, 2):
        cfg = SamplerConfig(thread_num=T, chunk_size=4)
        rep = ri.predict(spec_builder(16), cfg)
        res = engine.run(spec_builder(16), cfg)
        assert rep.prediction.matches_engine(res), T


# ---------------------------------------------------------------------------
# plateau ⊆ bracket: the r12 soundness regression (all 29 × T ∈ {1,2,4})
# ---------------------------------------------------------------------------

def test_exact_plateau_inside_bracket_all_families():
    """Predict-only (no engine): every registry family at every swept
    thread count must derive, reach its plateau, and land the exact
    plateau inside the PR-3 heuristic bracket — PL704 must never fire on
    the registry."""
    for name in sorted(REGISTRY):
        for T in (1, 2, 4):
            cfg = SamplerConfig(thread_num=T, chunk_size=4)
            rep = ri.predict(REGISTRY[name](16), cfg)
            assert rep.prediction.derivable, (name, T)
            assert rep.plateau is not None, (name, T)
            assert rep.plateau_in_bracket, (name, T)
            assert rep.bracket.c_lo <= rep.plateau <= rep.bracket.c_hi, \
                (name, T, rep.plateau, rep.bracket)
            assert not any(d.code == "PL704"
                           for d in rep.prediction.diagnostics)
            # the refined bracket collapses to the proven point
            refined = rep.refined_bracket
            assert refined.c_lo == refined.c_hi == rep.plateau


def test_refusals_are_typed_not_silent():
    # a spec outside the position contract must come back as a typed
    # PL701 refusal, never an exception or a silently-wrong histogram
    from pluss.spec import Loop, LoopNestSpec, Ref

    bad = LoopNestSpec("oob", (("A", 1),), (
        Loop(trip=8, bound_coef=(1, 1),
             body=(Ref("A0", "A", addr_terms=((0, 1),)),)),))
    pred = ri.derive(bad)
    assert not pred.derivable
    assert any(d.code == "PL701" for d in pred.diagnostics)

    # a derivable spec under a starvation budget refuses with PL702
    pred = ri.derive(REGISTRY["lu"](16), budget=16)
    assert not pred.derivable
    assert any(d.code == "PL702" for d in pred.diagnostics)


# ---------------------------------------------------------------------------
# zero device dispatches
# ---------------------------------------------------------------------------

def test_predict_makes_zero_device_dispatches(monkeypatch):
    # the witness counter must not move across both derivation rungs, and
    # the engine entry point must be unreachable from the predict path
    monkeypatch.setattr(engine, "run",
                        lambda *a, **k: pytest.fail(
                            "predict path called engine.run"))
    before = engine.DEVICE_DISPATCHES
    for name in ("gemm", "lu"):        # closed-form rung + dense rung
        rep = ri.predict(REGISTRY[name](16), SamplerConfig(thread_num=4))
        assert rep.prediction.derivable
    assert engine.DEVICE_DISPATCHES == before


# ---------------------------------------------------------------------------
# SARIF export (satellite 1)
# ---------------------------------------------------------------------------

def test_sarif_roundtrip_schema():
    cfg = SamplerConfig(thread_num=4)
    diags = []
    for name in ("gemm", "lu"):
        rep = ri.predict(REGISTRY[name](16), cfg)
        diags += rep.prediction.diagnostics
    doc = sarif.to_sarif(diags)
    # JSON round-trip: the export is plain data, losslessly serializable
    doc2 = json.loads(json.dumps(doc))
    assert doc2 == doc
    assert sarif.validate(doc2) == []
    run = doc2["runs"][0]
    assert doc2["version"] == "2.1.0"
    assert run["tool"]["driver"]["name"] == "pluss"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    for r in run["results"]:
        assert r["ruleId"] in rule_ids
        assert r["ruleId"] in CODES
        assert r["level"] in ("error", "warning", "note")
        assert r["message"]["text"]


def test_sarif_level_mapping_and_validate_rejects():
    d_err = Diagnostic("PL704", Severity.ERROR, "x", model="m")
    d_warn = Diagnostic("PL701", Severity.WARNING, "x", model="m")
    d_info = Diagnostic("PL703", Severity.INFO, "x", model="m")
    doc = sarif.to_sarif([d_err, d_warn, d_info])
    levels = [r["level"] for r in doc["runs"][0]["results"]]
    assert levels == ["error", "warning", "note"]
    # the structural validator actually rejects malformed documents
    assert sarif.validate({"version": "2.1.0", "runs": []})
    broken = json.loads(json.dumps(doc))
    broken["runs"][0]["results"][0]["ruleId"] = "PL999"
    assert sarif.validate(broken)


def test_sarif_write_and_cli_export(tmp_path, capsys):
    out = tmp_path / "predict.sarif"
    assert cli.main(["predict", "gemm", "--n", "16",
                     "--sarif", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert sarif.validate(doc) == []
    assert any(r["ruleId"] == "PL703"
               for r in doc["runs"][0]["results"])
    # lint rides the same flag
    out2 = tmp_path / "lint.sarif"
    assert cli.main(["lint", "--model", "durbin", "--n", "16",
                     "--sarif", str(out2)]) == 0
    assert sarif.validate(json.loads(out2.read_text())) == []


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_predict_text(capsys):
    assert cli.main(["predict", "gemm", "--n", "16"]) == 0
    out = capsys.readouterr().out
    assert "closed-form" in out
    assert "inside the bracket" in out
    assert "1/1 model(s) derivable" in out


def test_cli_predict_json(capsys):
    assert cli.main(["predict", "lu", "--n", "16", "--json",
                     "--threads", "2"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schedule"]["threads"] == 2
    m = doc["models"]["lu16"]
    assert m["derivable"] and m["method"] == "dense"
    assert m["plateau_in_bracket"] is True
    assert any(d["code"] == "PL703" for d in m["diagnostics"])


def test_cli_predict_check(capsys):
    # the run.sh gate shape, one model: engine cross-run must agree
    assert cli.main(["predict", "gemm", "--n", "16", "--check",
                     "--cpu"]) == 0
    err = capsys.readouterr().err
    assert "bit-identical" in err


def test_cli_predict_rejects_unknown_model():
    with pytest.raises(SystemExit):
        cli.main(["predict", "nosuchmodel"])
    with pytest.raises(SystemExit):
        cli.main(["predict", "gemm", "--all"])


def test_cli_analyze_carries_prediction_block(capsys):
    assert cli.main(["analyze", "--model", "gemm", "--n", "16",
                     "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    pred = doc["prediction"]["gemm16"]
    assert pred["derivable"] and pred["plateau_in_bracket"]
    # the exact plateau must sit inside the heuristic bounds reported by
    # the SAME document's footprint block (the cross-prover check, as a
    # consumer would apply it)
    lo, hi = doc["footprint"]["gemm16"]["mrc_plateau_bounds"]
    assert lo <= pred["mrc_plateau_exact"] <= hi


def test_cli_import_predict(capsys):
    # frontend-derived specs ride the same static path, still no device
    assert cli.main(["import",
                     "pluss/frontend/examples/gemm.ppcg_omp.c",
                     "--predict", "--n", "16"]) == 0
    out = capsys.readouterr().out
    assert "prediction closed-form" in out
    assert "inside the bracket" in out


def test_sweep_prediction_block():
    spec = REGISTRY["gemm"](16)
    pts = [sweep.SweepPoint(cfg=SamplerConfig(thread_num=T, chunk_size=4),
                            curve=np.zeros(1), total_refs=0)
           for T in (1, 2)]
    block = sweep.prediction_block(spec, pts)
    assert "static prediction (PL7xx):" in block
    assert "threads=1 chunk=4" in block and "threads=2 chunk=4" in block
    assert "OUTSIDE" not in block


# ---------------------------------------------------------------------------
# serve admission: static-cost pricing (tentpole wiring)
# ---------------------------------------------------------------------------

def test_serve_admission_static_cost(monkeypatch):
    from pluss.resilience.errors import InvalidRequest
    from pluss.serve.protocol import parse_request

    # generous stream bound, tiny cost bound: the request is now priced
    # by predicted refs + line_cost x footprint lines, not raw size
    monkeypatch.setenv("PLUSS_SERVE_MAX_COST", "1000")
    with pytest.raises(InvalidRequest) as ei:
        parse_request({"model": "gemm", "n": 16})
    assert "PLUSS_SERVE_MAX_COST" in str(ei.value)
    assert "static cost" in str(ei.value)
    # the line-cost weight is live: zero weight prices footprint out
    monkeypatch.setenv("PLUSS_SERVE_LINE_COST", "0")
    monkeypatch.setenv("PLUSS_SERVE_MAX_COST", "20000")
    parse_request({"model": "gemm", "n": 16})   # 16896 refs + 0*96 fits
    monkeypatch.setenv("PLUSS_SERVE_LINE_COST", "64")
    with pytest.raises(InvalidRequest):
        parse_request({"model": "gemm", "n": 16})  # + 64*96 does not
    # defaults admit the whole registry at bench sizes
    monkeypatch.delenv("PLUSS_SERVE_MAX_COST")
    monkeypatch.delenv("PLUSS_SERVE_LINE_COST")
    parse_request({"model": "gemm", "n": 16})


def test_serve_admission_refs_bound_still_first(monkeypatch):
    # the r07 PLUSS_SERVE_MAX_REFS contract is untouched: a stream-bound
    # violation still rejects with the original message, before cost
    from pluss.resilience.errors import InvalidRequest
    from pluss.serve.protocol import parse_request

    monkeypatch.setenv("PLUSS_SERVE_MAX_REFS", "1000")
    monkeypatch.setenv("PLUSS_SERVE_MAX_COST", "1")
    with pytest.raises(InvalidRequest) as ei:
        parse_request({"model": "gemm", "n": 16})
    assert "PLUSS_SERVE_MAX_REFS" in str(ei.value)


# ---------------------------------------------------------------------------
# full sweep (slow): every family + the frontend corpus vs the engine
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_full_registry_predict_matches_engine():
    cfg = SamplerConfig(thread_num=4, chunk_size=4)
    for name in sorted(REGISTRY):
        spec = REGISTRY[name](16)
        rep = ri.predict(spec, cfg)
        assert rep.prediction.derivable, name
        res = engine.run(spec, cfg)
        assert rep.prediction.matches_engine(res), name
        ok, detail = ri.check_against_engine(rep, res, cfg)
        assert ok, (name, detail)


@pytest.mark.slow
def test_frontend_imported_specs_ride_predict_path():
    from pluss.frontend import polybench

    cfg = SamplerConfig(thread_num=4, chunk_size=4)
    derived = 0
    for name, spec in sorted(polybench.import_polybench().items()):
        rep = ri.predict(spec, cfg)
        if not rep.prediction.derivable:
            # refusal must be typed, never an exception
            assert any(d.code in ("PL701", "PL702")
                       for d in rep.prediction.diagnostics), name
            continue
        derived += 1
        assert rep.plateau_in_bracket, name
        res = engine.run(spec, cfg)
        assert rep.prediction.matches_engine(res), name
        ok, detail = ri.check_against_engine(rep, res, cfg)
        assert ok, (name, detail)
    assert derived, "no polybench source derived — the path is dead"
