"""Test env: force an 8-device virtual CPU platform.

Multi-chip hardware is unavailable in CI; sharding semantics are validated on a
virtual 8-device CPU mesh exactly as SURVEY.md §7 prescribes.  The env vars are
set before JAX initializes AND the config is re-forced afterwards because this
image's sitecustomize registers a tunneled TPU backend that overrides
``JAX_PLATFORMS`` at startup.  f64 stays enabled: the CRI/statistics pipeline
matches C++ doubles (SURVEY.md §7 hard part 5).
"""

import os

os.environ.setdefault("JAX_ENABLE_X64", "1")

from pluss.utils.platform import force_cpu  # noqa: E402

force_cpu(n_virtual_devices=8)
