"""Test env: force an 8-device virtual CPU platform, x64 on.

Multi-chip hardware is unavailable in CI; sharding semantics are validated on a
virtual 8-device CPU mesh exactly as SURVEY.md §7 prescribes.  The config is
forced via ``jax.config.update`` (not env vars) because this image's
sitecustomize imports JAX at interpreter startup — ``JAX_ENABLE_X64`` /
``JAX_PLATFORMS`` set afterwards are silently ignored.  x64 on matches the
production entry points (cli/bench, which need int64 positions for >2^31
access streams); tests that need the x64-off behavior pin it off explicitly.
"""

import os  # noqa: E402

# plan artifacts (templates/overlays) must always rebuild under test — a
# stale cache entry could mask analysis bugs (tests that exercise the cache
# opt back in with PLUSS_PLAN_CACHE_DIR)
os.environ.setdefault("PLUSS_NO_PLAN_CACHE", "1")

# flight-recorder dumps triggered by breaker/watchdog tests must not litter
# the checkout (the server's default --flight-dir is the cwd); tests that
# assert on dump contents pin their own dir explicitly
import tempfile  # noqa: E402

os.environ.setdefault("PLUSS_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="pluss_test_flight_"))

from pluss.utils.platform import enable_x64, force_cpu  # noqa: E402

force_cpu(n_virtual_devices=8)
enable_x64()

# ---------------------------------------------------------------------------
# shard-backend startup probe: jax versions whose shard_map/collective API
# drifted (or an environment that cannot form the virtual mesh) must SKIP
# the sharded-backend tests with a reason, not fail them with raw
# AttributeErrors (the seed suite's 36 F's came from exactly this).

import pytest  # noqa: E402

from pluss.utils.compat import shard_backend_probe  # noqa: E402

#: None when the sharded backend works in this environment, else a reason
SHARD_UNAVAILABLE: str | None = shard_backend_probe()


def require_shard_backend() -> None:
    """Skip the calling test when the sharded backend is unusable here.

    For tests whose NAME does not say 'shard'/'multichip'/'multihost' but
    which still call shard_run internally — the name-keyed auto-skip below
    cannot see those."""
    if SHARD_UNAVAILABLE:
        pytest.skip(SHARD_UNAVAILABLE)


def pytest_collection_modifyitems(config, items):
    if not SHARD_UNAVAILABLE:
        return
    marker = pytest.mark.skip(reason=SHARD_UNAVAILABLE)
    for item in items:
        name = item.nodeid.lower()
        if any(k in name for k in ("shard", "multichip", "multihost")):
            item.add_marker(marker)
