"""r18: the loop-transformation legality prover, the spec-to-spec
transformer, and the transform-space tune search (`pluss transform`,
`pluss tune --transforms`, PL95x).

The load-bearing claims pinned here:

- the dependence-vector core is EXACT: its edge set for a nest equals
  the brute-force enumeration of every conflicting instance pair's
  direction pattern at small n;
- every transform the prover marks PL951-legal, applied, preserves the
  execution order of EVERY conflicting access pair (brute-force
  iteration-space oracle over the provenance instance mapping);
- every PL951 transformed spec run through the live engine matches its
  own static MRC prediction bit-identically — transformed specs ride
  the whole existing proof chain unchanged;
- every PL952 carries a CONCRETE violating pair the oracle confirms: a
  real same-address conflict, ordered src-before-dst originally, whose
  order the transform would reverse;
- nests outside the vector contract (triangular, quad) refuse with a
  typed PL953 cause chain — never a silent guess;
- `tune --transforms` finds a tiled gemm schedule with a strictly
  better predicted LLC miss ratio than the untransformed PL901 winner,
  with zero device dispatches during the search, and the winner's
  engine cross-check is bit-identical;
- the README documents the PL95x rows and legality rules this code
  actually ships (the code-table sync test covers the new family).
"""

import itertools
import json

import pytest

import tests.conftest  # noqa: F401  (CPU platform + x64)
from pluss import cli, engine, frontend, spec_codec
from pluss.analysis import depvec
from pluss.analysis import transform as tf
from pluss.analysis import tune as tune_mod
from pluss.analysis.diagnostics import CODES
from pluss.config import SamplerConfig
from pluss.model import hierarchy as hier_mod
from pluss.models import REGISTRY
from pluss.spec import Ref

BASE = SamplerConfig(thread_num=4, chunk_size=4)


# ---------------------------------------------------------------------------
# the brute-force iteration-space oracle


def enumerate_accesses(spec):
    """Serial access stream: (nest, name, values, array, addr, write)."""
    out = []

    def walk(body, values, ni):
        for x in body:
            if isinstance(x, Ref):
                addr = x.addr_base + sum(c * values[d]
                                         for d, c in x.addr_terms)
                out.append((ni, x.name, tuple(values), x.array, addr,
                            x.is_write))
            else:
                for i in range(x.trip):
                    walk(x.body, values + [x.start + x.step * i], ni)

    for ni, nest in enumerate(spec.nests):
        walk([nest], [], ni)
    return out


def order_violations(spec, rep):
    """All conflicting original pairs whose execution order the
    transformed spec reverses (empty = order-preserving).  Also asserts
    the provenance mapping is a bijection onto the original stream."""
    orig = enumerate_accesses(spec)
    trans = enumerate_accesses(rep.spec)
    mapper = tf.instance_mapper(rep.provenance)
    pos = {}
    for i, (ni, nm, vals, *_rest) in enumerate(orig):
        pos[(ni, nm, vals)] = i
    assert len(pos) == len(orig), "original instances are not unique"
    perm = [pos[mapper(ni, nm, vals)]
            for (ni, nm, vals, *_rest) in trans]
    assert sorted(perm) == list(range(len(orig))), (
        f"{rep.spec.name}: instance mapping is not a bijection "
        f"({len(perm)} mapped vs {len(orig)} original)")
    newpos = [0] * len(orig)
    for t, o in enumerate(perm):
        newpos[o] = t
    bygroup = {}
    for i, (_ni, _nm, _vals, arr, addr, w) in enumerate(orig):
        bygroup.setdefault((arr, addr), []).append((i, w))
    bad = []
    for g in bygroup.values():
        for (i, wi), (j, wj) in itertools.combinations(g, 2):
            if (wi or wj) and (newpos[i] < newpos[j]) != (i < j):
                bad.append((orig[i][:3], orig[j][:3]))
    return bad


LEGAL_CASES = [
    ("gemm", lambda s: tf.interchange(s, 0, 2)),
    ("gemm", lambda s: tf.interchange(s, 1, 2)),
    ("gemm", lambda s: tf.tile(s, [(0, 3), (1, 3), (2, 3)])),
    ("gemm", lambda s: tf.tile(s, [(2, 3)])),        # strip-mine only
    ("syrk", lambda s: tf.interchange(s, 0, 1)),
    ("syrk", lambda s: tf.tile(s, [(0, 3), (1, 3)])),
    ("2mm", lambda s: tf.fuse(s, 0, 1)),
    ("3mm", lambda s: tf.fuse(s, 0, 1)),
    ("mvt", lambda s: tf.fuse(s, 0, 1)),
    ("atax", lambda s: tf.fuse(s, 0, 1)),
    ("stencil3d", lambda s: tf.interchange(s, 1, 2)),
    ("heat3d", lambda s: tf.interchange(s, 1, 2)),
    ("floyd_warshall", lambda s: tf.interchange(s, 1, 2)),
    ("fdtd2d", lambda s: tf.fuse(s, 0, 1)),
]


@pytest.mark.parametrize("name,apply", LEGAL_CASES)
def test_legal_transform_preserves_dependence_order(name, apply):
    """Every PL951 verdict, checked exhaustively: the transformed
    iteration space executes every conflicting access pair in the
    original order."""
    spec = REGISTRY[name](6)
    rep = apply(spec)
    assert rep.code == "PL951", (name, rep.code, rep.diagnostics)
    assert rep.provenance is not None
    bad = order_violations(spec, rep)
    assert not bad, (
        f"{rep.spec.name}: {len(bad)} order violation(s), e.g. {bad[:3]}")


def test_depvec_edges_match_bruteforce_enumeration():
    """The vector core is exact: for each same-nest write-involving site
    pair, the prover's direction-pattern set equals the brute-force set
    realized by actual conflicting instance pairs."""
    for name in ("gemm", "jacobi2d", "seidel2d", "mvt"):
        spec = REGISTRY[name](5)
        acc = enumerate_accesses(spec)
        truth = set()
        for (n1, m1, v1, a1, ad1, w1), (n2, m2, v2, a2, ad2, w2) \
                in itertools.combinations(acc, 2):
            if n1 != n2 or a1 != a2 or ad1 != ad2 or not (w1 or w2):
                continue
            c = min(len(v1), len(v2))
            sigma = tuple((v2[k] > v1[k]) - (v2[k] < v1[k])
                          for k in range(c))
            if m1 == m2 and all(s == 0 for s in sigma):
                continue  # same instance
            truth.add((m1, m2, sigma))
        vecs = depvec.spec_vectors(spec)
        got = set()
        for nv in vecs:
            assert nv.refused is None, (name, nv.refused)
            for e in nv.edges:
                got.add((e.src.ref.name, e.dst.ref.name, e.sigma))
        # normalize truth the way the prover does: source is the
        # program-earlier access, vector lex-nonnegative
        norm = set()
        for m1, m2, sigma in truth:
            lex = next((1 if s > 0 else -1 for s in sigma if s), 0)
            if lex < 0:
                norm.add((m2, m1, tuple(-s for s in sigma)))
            else:
                norm.add((m1, m2, sigma))
        assert got == norm, (name, got ^ norm)


# ---------------------------------------------------------------------------
# engine bit-identity of transformed specs (>= 6 families x 3 kinds)


ENGINE_CASES = [
    # heaviest entry → slow tier; interchange bit-identity stays in
    # tier-1 via the syrk case, gemm via the tile case
    pytest.param("gemm", lambda s: tf.interchange(s, 0, 2),
                 marks=pytest.mark.slow),
    ("gemm", lambda s: tf.tile(s, [(0, 4), (1, 4), (2, 4)])),
    ("syrk", lambda s: tf.interchange(s, 0, 1)),
    ("syrk", lambda s: tf.tile(s, [(0, 4), (1, 4)])),
    ("2mm", lambda s: tf.fuse(s, 0, 1)),
    ("3mm", lambda s: tf.fuse(s, 0, 1)),
    ("mvt", lambda s: tf.fuse(s, 0, 1)),
    ("stencil3d", lambda s: tf.interchange(s, 1, 2)),
    ("heat3d", lambda s: tf.interchange(s, 1, 2)),
    ("atax", lambda s: tf.fuse(s, 0, 1)),
]


@pytest.mark.parametrize("name,apply", ENGINE_CASES)
def test_transformed_spec_engine_check_bit_identical(name, apply):
    """A PL951 spec is an ordinary spec: the live engine run matches the
    static MRC prediction of the TRANSFORMED nest bit-identically."""
    rep = apply(REGISTRY[name](8))
    assert rep.code == "PL951", (name, rep.code, rep.diagnostics)
    ok, detail, diags = tf.check_transform(rep, BASE)
    assert not detail.get("skipped"), (name, detail)
    assert ok, (name, detail, [d.message for d in diags])
    assert detail["histogram_identical"], (name, detail)


# ---------------------------------------------------------------------------
# PL952: the violating pair is oracle-real


def _site_of(spec, ni, name):
    (site,) = [s for s in depvec.ref_sites(spec)
               if s.nest == ni and s.ref.name == name]
    return site


def _addr_at(site, iv):
    values = [l.start + l.step * i for l, i in zip(site.chain, iv)]
    return site.ref.addr_base + sum(c * values[d]
                                    for d, c in site.ref.addr_terms)


ILLEGAL_CASES = [
    ("seidel2d", lambda s: tf.interchange(s, 0, 1)),
    ("seidel2d", lambda s: tf.interchange(s, 0, 2)),
    ("floyd_warshall", lambda s: tf.interchange(s, 0, 1)),
    ("floyd_warshall", lambda s: tf.interchange(s, 0, 2)),
    ("jacobi2d", lambda s: tf.fuse(s, 0, 1)),
    ("3mm", lambda s: tf.fuse(s, 1, 2)),
    ("gemver", lambda s: tf.fuse(s, 0, 1)),
]


@pytest.mark.parametrize("name,apply", ILLEGAL_CASES)
def test_pl952_violating_pair_is_oracle_confirmed(name, apply):
    """Every proven-illegal verdict carries a concrete witness pair the
    brute-force semantics confirm: a real same-address conflict, with at
    least one write, src executing before dst, whose order the transform
    would reverse."""
    spec = REGISTRY[name](8)
    rep = apply(spec)
    assert rep.code == "PL952", (name, rep.code, rep.diagnostics)
    v = rep.violation
    assert v is not None
    src_iv, dst_iv = tuple(v["src_iv"]), tuple(v["dst_iv"])
    if rep.kind == "fuse":
        na, nb = rep.params["a"], rep.params["b"]
        src = _site_of(spec, na, v["src"])
        dst = _site_of(spec, nb, v["dst"])
    else:
        ni = rep.params["nest"]
        src = _site_of(spec, ni, v["src"])
        dst = _site_of(spec, ni, v["dst"])
    # in-range witness instances on a REAL conflict
    for site, iv in ((src, src_iv), (dst, dst_iv)):
        assert len(iv) == len(site.chain)
        assert all(0 <= i < l.trip for i, l in zip(iv, site.chain)), (
            name, iv)
    assert src.ref.array == dst.ref.array
    assert src.ref.is_write or dst.ref.is_write
    assert _addr_at(src, src_iv) == _addr_at(dst, dst_iv), (
        name, "witness pair does not collide")
    if rep.kind == "fuse":
        # src's nest runs first today; fused, the dst instance at the
        # strictly smaller outer index would run before its source
        assert dst_iv[0] < src_iv[0], (name, src_iv, dst_iv)
    else:
        c = len(v["vector"])
        assert src_iv[:c] <= dst_iv[:c], "src must execute first"
        a, b = rep.params["a"], rep.params["b"]
        ps, pd = list(src_iv[:c]), list(dst_iv[:c])
        ps[a], ps[b] = ps[b], ps[a]
        pd[a], pd[b] = pd[b], pd[a]
        assert pd < ps, (
            name, "swap does not reverse the witness pair's order")


# ---------------------------------------------------------------------------
# PL953: typed refusals, never silent guesses


@pytest.mark.parametrize("name", ["trmm", "syrk_tri", "cholesky",
                                  "ludcmp", "covariance"])
def test_triangular_and_quad_nests_refuse_typed(name):
    spec = REGISTRY[name](8)
    for rep in (tf.interchange(spec, 0, 1), tf.tile(spec, [(0, 2)])):
        assert rep.code == "PL953", (name, rep.code)
        assert rep.spec is None
        (d,) = [g for g in rep.diagnostics if g.code == "PL953"]
        assert "contract" in d.message or "refused" in d.message


def test_budget_exhaustion_refuses_typed(monkeypatch):
    monkeypatch.setenv("PLUSS_DEPVEC_BUDGET", "1")
    rep = tf.interchange(REGISTRY["gemm"](8), 0, 2)
    assert rep.code == "PL953"
    assert "budget" in rep.diagnostics[0].message.lower()


def test_malformed_cli_params_raise():
    with pytest.raises(ValueError):
        tf.parse_interchange("0")
    with pytest.raises(ValueError):
        tf.parse_tile("0-8")
    with pytest.raises(ValueError):
        tf.parse_fuse("0")


# ---------------------------------------------------------------------------
# the transform-space search (tune --transforms)


def test_search_transforms_beats_untransformed_gemm():
    """The r18 acceptance pin: at a 1 KB LLC the search proves a tiled
    gemm schedule strictly better than the untransformed PL901 winner,
    with ZERO device dispatches, and the engine confirms the winner's
    prediction bit-identically."""
    spec = REGISTRY["gemm"](64)
    hier = hier_mod.HierarchyConfig(levels_kb=(1,), assoc=0, policy="lru")
    cands = tune_mod.space((1, 2, 4), (1, 4))
    d0 = engine.DEVICE_DISPATCHES
    rep = tf.search_transforms(spec, candidates=cands, hier=hier)
    assert engine.DEVICE_DISPATCHES == d0, "search touched the device"
    assert rep.best is not None, [d.message for d in rep.diagnostics]
    assert rep.best.transform.kind == "tile"
    base_score = rep.base.winner.score
    assert rep.best.score() < base_score - tune_mod.TIE_EPS
    assert rep.delta == rep.best.score() - base_score
    ok, detail, _ = tune_mod.check_winner(rep.best.transform.spec,
                                          rep.best.tune)
    assert ok, detail
    assert detail["histogram_identical"] and detail["mrc_exact"], detail


def test_search_transforms_doc_shape():
    spec = REGISTRY["gemm"](16)
    rep = tf.search_transforms(spec, candidates=tune_mod.space((1, 2),
                                                               (1,)))
    doc = rep.doc()
    assert doc["model"] == "gemm16"
    assert doc["base"]["verdict"] in ("PL901", "PL902")
    assert doc["transforms"], "transform space must not be empty"
    for e in doc["transforms"]:
        assert e["verdict"] in ("PL951", "PL952", "PL953")
    json.dumps(doc)  # the whole report must be JSON-serializable


def test_tile_ladder_sizes_divide_and_fit():
    spec = REGISTRY["gemm"](64)
    hier = hier_mod.HierarchyConfig(levels_kb=(1, 32), assoc=0,
                                    policy="lru")
    trips = [64, 64, 64]
    sizes = tf.tile_ladder(spec, trips, BASE, hier)
    assert sizes, "ladder empty for a hierarchy that fits tiles"
    for s in sizes:
        assert 2 <= s < 64 and 64 % s == 0


# ---------------------------------------------------------------------------
# transformed specs are ordinary specs (registerable, emittable)


def test_transformed_spec_registers_and_reloads(tmp_path):
    rep = tf.tile(REGISTRY["gemm"](32), [(0, 8), (1, 8), (2, 8)])
    path = tmp_path / f"{rep.spec.name}.json"
    path.write_text(spec_codec.dump_spec(rep.spec) + "\n")
    reloaded = spec_codec.load_spec_file(str(path))
    assert spec_codec.specs_equal(reloaded, rep.spec)


def test_transform_share_spans_rederived_not_stale():
    """The transformer re-derives share_span through the frontend
    pipeline: a tiled gemm's spans must equal the derivation on the
    tiled nest itself (derive_spans is a fixed point), never the
    original nest's copied values."""
    from pluss.frontend.lower import derive_spans

    rep = tf.tile(REGISTRY["gemm"](32), [(0, 4), (1, 4), (2, 4)])
    assert rep.code == "PL951"
    assert spec_codec.specs_equal(derive_spans(rep.spec), rep.spec)


# ---------------------------------------------------------------------------
# CLI surfaces


def test_cli_transform_legal(capsys):
    rc = cli.main(["transform", "gemm", "--interchange", "0,2",
                   "--n", "16"])
    outerr = capsys.readouterr()
    assert rc == 0
    assert "PL951" in outerr.out
    assert "gemm16_ic02" in outerr.out


def test_cli_transform_illegal_exits_nonzero(capsys):
    rc = cli.main(["transform", "seidel2d", "--interchange", "0,1",
                   "--n", "8"])
    outerr = capsys.readouterr()
    assert rc == 1
    assert "PL952" in outerr.out
    assert "violating pair" in outerr.out


def test_cli_transform_refusal_exits_nonzero(capsys):
    rc = cli.main(["transform", "trmm", "--interchange", "0,1",
                   "--n", "8"])
    outerr = capsys.readouterr()
    assert rc == 1
    assert "PL953" in outerr.out


def test_cli_transform_json_carries_spec_and_edges(capsys):
    rc = cli.main(["transform", "gemm", "--tile", "0:4,1:4,2:4",
                   "--n", "16", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["verdict"] == "PL951"
    assert doc["kind"] == "tile"
    assert doc["edges"], "witness vectors must ride the JSON doc"
    assert doc["spec"]["name"] == "gemm16_tile0x4_1x4_2x4"


def test_cli_transform_check_engine(capsys):
    rc = cli.main(["transform", "gemm", "--interchange", "0,2",
                   "--n", "16", "--check", "--cpu"])
    outerr = capsys.readouterr()
    assert rc == 0
    assert "verified against engine.run" in outerr.err
    assert "bit-identical" in outerr.err


def test_cli_transform_sarif(tmp_path):
    from pluss.analysis import sarif

    log = tmp_path / "transform.sarif"
    rc = cli.main(["transform", "gemm", "--interchange", "0,2",
                   "--n", "16", "--sarif", str(log)])
    assert rc == 0
    doc = json.loads(log.read_text())
    assert sarif.validate(doc) == []
    rules = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert "PL951" in rules


def test_cli_transform_register(tmp_path, capsys):
    rc = cli.main(["transform", "gemm", "--tile", "0:8,1:8,2:8",
                   "--n", "32", "--register", "--registry-dir",
                   str(tmp_path)])
    outerr = capsys.readouterr()
    assert rc == 0
    assert "registered gemm32_tile0x8_1x8_2x8" in outerr.err
    reloaded = spec_codec.load_spec_file(
        str(tmp_path / "gemm32_tile0x8_1x8_2x8.json"))
    assert reloaded.name == "gemm32_tile0x8_1x8_2x8"


def test_cli_transform_wants_exactly_one_flag():
    with pytest.raises(SystemExit):
        cli.main(["transform", "gemm", "--n", "16"])
    with pytest.raises(SystemExit):
        cli.main(["transform", "gemm", "--interchange", "0,1",
                  "--tile", "0:4", "--n", "16"])
    with pytest.raises(SystemExit):
        cli.main(["transform", "nosuch", "--interchange", "0,1"])


def test_cli_tune_transforms(capsys):
    rc = cli.main(["tune", "gemm", "--transforms", "--n", "16",
                   "--sweep-threads", "1,2", "--sweep-chunks", "1"])
    outerr = capsys.readouterr()
    assert rc == 0
    assert "transform space" in outerr.out
    with pytest.raises(SystemExit):
        cli.main(["tune", "--all", "--transforms", "--n", "16"])


def test_cli_analyze_surfaces_depvectors(capsys):
    rc = cli.main(["analyze", "--model", "gemm", "--n", "16", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    dv = doc["depvectors"]["gemm16"]
    assert dv["edges"] > 0
    edge = dv["nests"][0]["edges"][0]
    for key in ("src", "dst", "array", "kind", "vector", "distance",
                "src_iv", "dst_iv"):
        assert key in edge


def test_cli_analyze_race_findings_carry_vectors(capsys):
    rc = cli.main(["analyze", "--model", "atax", "--n", "16"])
    out = capsys.readouterr().out
    assert rc == 0
    race_lines = [l for l in out.splitlines()
                  if "PL301" in l or "PL302" in l]
    assert race_lines
    assert all("dep vectors:" in l for l in race_lines), race_lines


# ---------------------------------------------------------------------------
# diagnostics registry


def test_pl95x_codes_registered():
    for code in ("PL951", "PL952", "PL953", "PL954"):
        family, _ = CODES[code]
        assert family == "transform"


def test_emitted_transformed_dsl_reimports():
    """The emit_dsl round-trip of a tiled spec rides the real import
    path end to end (frontend.from_py), not just the codec."""
    rep = tf.tile(REGISTRY["gemm"](32), [(0, 8), (1, 8), (2, 8)])
    (re_,) = frontend.from_py(frontend.emit_dsl(rep.spec))
    assert spec_codec.specs_equal(re_, rep.spec)
