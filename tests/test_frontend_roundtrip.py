"""The DSL grammar-coverage pin: every hand-written registry family,
re-emitted as DSL source (``frontend.emit_dsl``), re-executed through
the DSL, re-lowered — and codec-equal to the original.  If a future
spec feature (a new Loop field, a new Ref annotation) is not
representable in the DSL, this suite fails on the family that uses it.
"""

import pytest

import tests.conftest  # noqa: F401
from pluss import frontend, spec_codec
from pluss.models import REGISTRY


@pytest.mark.parametrize("name", sorted(REGISTRY))
def test_registry_family_roundtrips_through_dsl(name):
    spec = REGISTRY[name]()       # the default size — what run.sh lints
    src = frontend.emit_dsl(spec)
    (reparsed,) = frontend.from_py(src, filename=f"<emit:{name}>")
    assert spec_codec.spec_to_json(reparsed) \
        == spec_codec.spec_to_json(spec), (
        f"{name}: emit_dsl -> from_py is not the identity")


@pytest.mark.parametrize("name", ["gemm", "syrk_tri", "cholesky",
                                  "ludcmp", "covariance"])
def test_roundtrip_at_off_default_sizes(name):
    # the tricky shapes (triangular, quad, descending-parallel) at a
    # second size, so the emitter's bound algebra is not size-lucky
    spec = REGISTRY[name](24)
    src = frontend.emit_dsl(spec)
    (reparsed,) = frontend.from_py(src)
    assert spec_codec.specs_equal(reparsed, spec)


def test_emitted_source_is_plain_dsl():
    # the emitted text uses only the documented surface (kernel/array/
    # loop/read/write [+ loop_raw escape hatch]), so it doubles as
    # authoring documentation
    src = frontend.emit_dsl(REGISTRY["trmm"](16))
    assert "frontend.kernel(" in src
    assert "frontend.loop(" in src
    assert "auto_span=False" in src
    # no registry family needs the raw escape hatch
    assert "loop_raw" not in src


def test_roundtrip_preserves_spans_without_auto_derivation():
    # emitted sources carry explicit spans and auto_span=False: a family
    # whose hand annotation DIFFERS from the derived convention (e.g.
    # refs the race detector flags but the author left span-less) must
    # round-trip to the hand-written truth, not to the derivation
    spec = REGISTRY["conv2d"]()
    (reparsed,) = frontend.from_py(frontend.emit_dsl(spec))
    assert spec_codec.specs_equal(reparsed, spec)


# --- transformed specs (r18): tiling introduces synthetic non-unit-stride
# tile loops; the emitter must express them via the plain `step=` sugar,
# never the loop_raw escape hatch -------------------------------------------


@pytest.mark.parametrize("name,tiles", [
    ("gemm", [(0, 8), (1, 8), (2, 8)]),   # full-band (parallel loop strided)
    ("gemm", [(2, 8)]),                   # innermost strip-mine only
    ("syrk", [(0, 8), (1, 8)]),           # write-carrying band
    ("stencil3d", [(1, 5), (2, 5)]),      # nonzero-start inner loops
])
def test_tiled_spec_roundtrips_through_dsl(name, tiles):
    from pluss.analysis import transform as tf

    spec = REGISTRY[name](32)
    rep = tf.tile(spec, tiles)
    assert rep.code == "PL951", rep.diagnostics
    src = frontend.emit_dsl(rep.spec)
    assert "loop_raw" not in src, "tile loops must emit as step= sugar"
    (reparsed,) = frontend.from_py(src, filename=f"<emit:{rep.spec.name}>")
    assert spec_codec.specs_equal(reparsed, rep.spec), (
        f"{rep.spec.name}: emit_dsl -> from_py is not the identity")


@pytest.mark.parametrize("name,apply", [
    ("gemm", lambda tf, s: tf.interchange(s, 0, 2)),
    ("2mm", lambda tf, s: tf.fuse(s, 0, 1)),   # fusion renames colliding refs
])
def test_other_transforms_roundtrip_through_dsl(name, apply):
    from pluss.analysis import transform as tf

    rep = apply(tf, REGISTRY[name](32))
    assert rep.code == "PL951", rep.diagnostics
    (reparsed,) = frontend.from_py(frontend.emit_dsl(rep.spec))
    assert spec_codec.specs_equal(reparsed, rep.spec)
