"""Persisted batch-geometry autotuner (r19): sidecar round-trip, salt
invalidation, corrupt quarantine, dry-run exit codes, forced-probe
fallback during calibration, and the README / stats-block sync.
"""

import io
import json
import os

import pytest

from pluss import autotune


@pytest.fixture
def plan_cache(tmp_path, monkeypatch):
    """Opt back into the plan cache (conftest disables it) with a private
    root, and forget memoized sidecar loads on both sides."""
    monkeypatch.delenv("PLUSS_NO_PLAN_CACHE", raising=False)
    monkeypatch.setenv("PLUSS_PLAN_CACHE_DIR", str(tmp_path))
    autotune.invalidate()
    yield tmp_path
    autotune.invalidate()


@pytest.fixture
def counters(tmp_path):
    """An active telemetry session; yields a snapshot callable."""
    from pluss import obs

    obs.shutdown()
    obs.configure(str(tmp_path / "telemetry.jsonl"))
    yield obs.counters
    obs.shutdown()


def _valid_doc():
    from pluss import plancache

    return {
        "version": 1,
        "salt": plancache.runtime_salt(),
        "geometry": {"window": 4096, "batch_windows": 2, "stage_depth": 2,
                     "queue_depth": 2, "feed_workers": 1, "wire": "pack",
                     "pallas": False},
        "refs_per_sec": 1234.5,
        "calibration": {"n_refs": 4096, "points": 1, "elapsed_s": 0.1},
    }


def test_sidecar_roundtrip(plan_cache):
    """_save → consult round-trips every geometry field; the sidecar
    lands under the plan-cache root, salt-keyed."""
    path = autotune._save(_valid_doc())
    assert path is not None and os.path.exists(path)
    assert os.path.dirname(path) == str(plan_cache)
    assert os.path.basename(path).startswith("autotune-")
    geo = _valid_doc()["geometry"]
    for k, v in geo.items():
        assert autotune.consult(k) == v
    assert autotune.tuned_geometry() == geo
    assert autotune.consult("no_such_field") is None


def test_no_plan_cache_means_no_sidecar(monkeypatch):
    monkeypatch.setenv("PLUSS_NO_PLAN_CACHE", "1")
    autotune.invalidate()
    assert autotune.sidecar_path() is None
    assert autotune.consult("window") is None
    assert autotune._save(_valid_doc()) is None


def test_hit_counted_once_per_process(plan_cache, counters):
    """Consults are memoized: many lookups, ONE disk read, ONE
    autotune.hit — the witness run.sh checks for zero re-calibration."""
    autotune._save(_valid_doc())
    autotune.invalidate()
    for _ in range(5):
        assert autotune.consult("window") == 4096
    assert counters().get("autotune.hit") == 1
    assert not counters().get("autotune.stale")


def test_salt_mismatch_is_a_stale_miss(plan_cache, counters, capsys):
    """A sidecar calibrated on a different runtime is ignored (counted
    stale, one stderr notice) but NOT quarantined — it may be valid for
    the runtime that wrote it."""
    doc = _valid_doc()
    doc["salt"] = "jax=0.0.0/other/other/nbins=1"
    path = autotune.sidecar_path()
    with open(path, "w") as f:
        json.dump(doc, f)
    assert autotune.consult("window") is None
    assert autotune.tuned_geometry() is None
    assert counters().get("autotune.stale") == 1
    assert "different runtime" in capsys.readouterr().err
    assert os.path.exists(path)          # left in place, not quarantined


def test_corrupt_sidecar_quarantined(plan_cache, counters, capsys):
    """Unparseable bytes: counted stale, renamed to .corrupt, consult
    returns None — never a crash."""
    path = autotune.sidecar_path()
    with open(path, "wb") as f:
        f.write(b"\x00not json{{{")
    assert autotune.consult("window") is None
    assert counters().get("autotune.stale") == 1
    assert not os.path.exists(path)
    assert os.path.exists(path + ".corrupt")
    assert "recalibrate" in capsys.readouterr().err


def test_invalid_geometry_field_quarantined(plan_cache, counters):
    """Schema validation bites: a parseable doc with an out-of-domain
    field (wire not in pack/d24v) is quarantined like corrupt bytes."""
    doc = _valid_doc()
    doc["geometry"]["wire"] = "carrier-pigeon"
    path = autotune.sidecar_path()
    with open(path, "w") as f:
        json.dump(doc, f)
    assert autotune.consult("wire") is None
    assert counters().get("autotune.stale") == 1
    assert os.path.exists(path + ".corrupt")


def test_consult_disabled_by_env(plan_cache, monkeypatch):
    autotune._save(_valid_doc())
    monkeypatch.setenv("PLUSS_AUTOTUNE", "0")
    autotune.invalidate()
    assert autotune.consult("window") is None
    monkeypatch.delenv("PLUSS_AUTOTUNE")
    autotune.invalidate()
    assert autotune.consult("window") == 4096


def test_dry_run_exit_codes(plan_cache, monkeypatch):
    """0 for 'no sidecar yet' and for a valid one; 1 only when a file
    exists but fails validation (the run.sh gate's broken-artifact
    signal)."""
    buf = io.StringIO()
    assert autotune.dry_run(buf) == 0
    assert "no sidecar yet" in buf.getvalue()

    autotune._save(_valid_doc())
    buf = io.StringIO()
    assert autotune.dry_run(buf) == 0
    out = buf.getvalue()
    assert "valid sidecar" in out and "window" in out

    path = autotune.sidecar_path()
    with open(path, "w") as f:
        f.write("not json")
    buf = io.StringIO()
    assert autotune.dry_run(buf) == 1
    assert "failed validation" in buf.getvalue()

    monkeypatch.setenv("PLUSS_NO_PLAN_CACHE", "1")
    buf = io.StringIO()
    assert autotune.dry_run(buf) == 0
    assert "plan cache disabled" in buf.getvalue()


def test_calibrate_short_circuits_on_valid_sidecar(plan_cache, monkeypatch):
    """An existing valid sidecar means ZERO re-calibration: _time_point
    must never run without --force."""
    autotune._save(_valid_doc())
    autotune.invalidate()

    def boom(*a, **k):
        raise AssertionError("calibration ran despite a valid sidecar")

    monkeypatch.setattr(autotune, "_time_point", boom)
    buf = io.StringIO()
    doc = autotune.calibrate(out=buf)
    assert doc["geometry"] == _valid_doc()["geometry"]
    assert "already persisted" in buf.getvalue()


def test_calibrate_persists_winner(plan_cache, monkeypatch, counters):
    """A short real calibration (one candidate, two tiny replays)
    persists a schema-valid winner that the next consult serves."""
    monkeypatch.setattr(autotune, "_candidates",
                        lambda base: [dict(base, pallas=False)])
    buf = io.StringIO()
    doc = autotune.calibrate(n_refs=16384, out=buf)
    assert doc["version"] == 1
    for k, ok in autotune._FIELDS.items():
        assert ok(doc["geometry"][k]), (k, doc["geometry"][k])
    assert counters().get("autotune.probe") == 1
    assert os.path.exists(autotune.sidecar_path())
    autotune.invalidate()
    assert autotune.tuned_geometry() == doc["geometry"]
    # the persisted winner now short-circuits a second calibrate
    buf = io.StringIO()
    again = autotune.calibrate(out=buf)
    assert again["geometry"] == doc["geometry"]
    assert "already persisted" in buf.getvalue()


def test_calibrate_forced_probe_falls_back_to_xla(plan_cache, monkeypatch,
                                                  counters, capsys):
    """A pallas=True calibration point on a runtime whose Pallas probe
    fails must degrade to the XLA path (loud, counted) and still produce
    a winner — calibration can never crash on a broken lowering."""
    from pluss.ops import pallas_decode, pallas_events

    def boom(*a, **k):
        raise RuntimeError("synthetic lowering failure")

    monkeypatch.setattr(pallas_events, "_probe_impl", boom)
    monkeypatch.setattr(pallas_decode, "_probe_impl", boom)
    pallas_events.reset_probe()
    pallas_decode.reset_probe()
    monkeypatch.setattr(autotune, "_candidates",
                        lambda base: [dict(base, pallas=True)])
    try:
        doc = autotune.calibrate(n_refs=16384, force=True,
                                 out=io.StringIO())
    finally:
        monkeypatch.undo()
        pallas_events.reset_probe()
        pallas_decode.reset_probe()
    assert doc["geometry"]["pallas"] is True     # the knob, as requested
    assert counters().get("pallas.fallback", 0) >= 1
    assert "using the XLA path" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# stats block + README sync


def test_stats_autotune_breakdown_render():
    from pluss.obs.stats import autotune_breakdown

    assert autotune_breakdown({}, {}) == []
    counters = {"pallas.probe": 2.0, "pallas.fallback": 0.0,
                "autotune.probe": 9.0, "autotune.hit": 1.0,
                "autotune.stale": 0.0}
    lines = autotune_breakdown(counters, {})
    assert lines[0] == "kernels & autotune:"
    text = "\n".join(lines)
    assert "pallas probes / fallbacks" in text and "2 / 0" in text
    assert "DISABLED" not in text
    assert "geometry hits / stale" in text and "1 / 0" in text
    assert "calibration points timed" in text and "9" in text

    broken = autotune_breakdown({"pallas.probe": 1.0,
                                 "pallas.fallback": 1.0}, {})
    assert "fused kernels DISABLED, XLA path" in "\n".join(broken)


def test_readme_documents_kernels_and_autotune():
    """README's 'TPU-native kernels & autotuning' section must name every
    knob and counter this subsystem emits — the doc is the operator's
    only map."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(here, "README.md")) as f:
        readme = f.read()
    start = readme.index("## TPU-native kernels & autotuning")
    end = readme.index("\n## ", start + 1)
    section = readme[start:end]
    for knob in ("PLUSS_PALLAS_EVENTS", "PLUSS_PALLAS_DECODE",
                 "PLUSS_AUTOTUNE"):
        assert knob in section, knob
    for counter in ("pallas.probe", "pallas.fallback", "autotune.hit",
                    "autotune.stale"):
        assert counter in section, counter
    assert "kernels & autotune:" in section
    assert "pluss autotune" in section
