"""The driver's contract: entry() jit-compiles, dryrun_multichip(8) passes."""

import jax
import pytest

import __graft_entry__ as ge


def test_entry_compiles_and_runs():
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)   # packed [T, L] result matrix
    from pluss.config import NBINS

    hist = out[:, :NBINS]
    assert hist.shape[0] == 4
    # total no-share + cold events of GEMM-128 (8,421,376 accesses minus the
    # share events) must be positive on every simulated thread
    assert (hist.sum(axis=1) > 0).all()


def test_dryrun_multichip():
    # dryrun_multichip pins an 8-device virtual CPU mesh itself
    ge.dryrun_multichip(8)


@pytest.mark.slow  # tier-1 keeps test_dryrun_multichip (full mesh) as
# the dry-run representative
def test_dryrun_multichip_small_meshes():
    # smaller meshes than the initialized device count must also hold (XLA
    # reads the virtual-device-count flag once per process, so counts can
    # only descend within a process — growth raises, tested below)
    ge.dryrun_multichip(4)
    ge.dryrun_multichip(2)


def test_virtual_device_growth_raises():
    import pytest

    from pluss.utils.platform import force_cpu

    import jax

    n = len(jax.devices())
    with pytest.raises(RuntimeError, match="cannot grow"):
        force_cpu(n + 1)
