// Probe harness around the REFERENCE ChunkDispatcher (not a reimplementation):
// drives setStartPoint + getStaticStartChunk on the actual class from
// /root/reference/c_lib/test/runtime/pluss_utils.h so the Python
// ChunkSchedule.static_start_chunk can be diffed against the original
// per-tid rounding semantics (pluss_utils.h:443-490).
//
// usage: dispatcher_probe trip start step i
// prints one "lb ub" line per tid.
#include <cstdio>
#include <cstdlib>
#include "pluss_utils.h"

int main(int argc, char **argv) {
    if (argc != 5) return 2;
    int trip = atoi(argv[1]), start = atoi(argv[2]);
    int step = atoi(argv[3]), i = atoi(argv[4]);
    std::ChunkDispatcher d(CHUNK_SIZE, trip, start, step);
    d.setStartPoint(i);
    for (int t = 0; t < THREAD_NUM; t++) {
        std::Chunk c = d.getStaticStartChunk(i, t);
        printf("%d %d\n", c.first, c.second);
    }
    return 0;
}
