"""Triangular (value-dependent bound) nests: PolyBench 4.2 syrk's ``j <= i``.

The reference has no triangular sampler (its one workload is rectangular
GEMM), so this is capability-surface extension: ``Loop.bound_coef`` keeps
every stream position affine in the parallel index, plus one per-thread
clock table for the varying body size (engine.plan).  Every backend must
agree with the pure-Python oracle.
"""

import numpy as np
import pytest

from pluss import engine, native
from pluss.config import SamplerConfig
from pluss.models import syrk_triangular
from pluss.spec import Loop, LoopNestSpec, Ref, flatten_nest

from tests.oracle import OracleSampler  # noqa: F401  (spec fixtures below)
from tests.oracle import assert_result_matches_oracle as assert_matches_oracle


@pytest.mark.parametrize("n,cls", [(8, 8), (12, 64), (13, 8)])
def test_engine_matches_oracle(n, cls):
    spec = syrk_triangular(n)
    cfg = SamplerConfig(cls=cls)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


def test_total_count_closed_form():
    # syrk_tri body: per i, (2 + 4n) * (i+1) accesses
    n = 8
    res = engine.run(syrk_triangular(n), SamplerConfig())
    expect = (2 + 4 * n) * n * (n + 1) // 2
    assert res.max_iteration_count == expect


def test_engine_windowed_scan_matches_oracle():
    # tiny windows force multi-window scans with the triangular clock table
    spec = syrk_triangular(16)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg, window_accesses=1))


def test_seq_backend_matches_oracle():
    spec = syrk_triangular(8)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg, backend="seq"))


def test_shard_matches_engine():
    from pluss.parallel.shard import default_mesh, shard_run

    spec = syrk_triangular(16)
    cfg = SamplerConfig(cls=8)
    a = engine.run(spec, cfg)
    b = shard_run(spec, cfg, mesh=default_mesh(4))
    assert a.max_iteration_count == b.max_iteration_count
    assert a.noshare_dense.tolist() == b.noshare_dense.tolist()
    assert a.share_raw == b.share_raw
    # forced sub-windows: the clock table rides the intra-device scan too
    c = shard_run(spec, cfg, mesh=default_mesh(2), window_accesses=1)
    assert a.noshare_dense.tolist() == c.noshare_dense.tolist()
    assert a.share_raw == c.share_raw


def test_native_matches_engine():
    if not native.available(autobuild=True):
        pytest.skip("native runtime unavailable")
    spec = syrk_triangular(13)
    cfg = SamplerConfig(cls=8)
    a = engine.run(spec, cfg)
    b = native.run(spec, cfg)
    assert a.noshare_list() == b.noshare_list()
    assert a.share_list() == b.share_list()


def test_sampled_run_single_window_exact():
    from pluss import sampling

    spec = syrk_triangular(8)
    cfg = SamplerConfig(cls=8)
    full = engine.run(spec, cfg)
    est = sampling.sampled_run(spec, cfg, rate=1.0)
    assert np.array_equal(est.noshare_dense, full.noshare_dense)


@pytest.mark.parametrize("n,cls", [(8, 8), (13, 64)])
def test_trmm_matches_oracle(n, cls):
    # varying START (k from i+1) on top of the varying trip: Loop.start_coef
    from pluss.models import trmm

    spec = trmm(n)
    cfg = SamplerConfig(cls=cls)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


def test_trmm_shard_matches_engine():
    from pluss.models import trmm
    from pluss.parallel.shard import default_mesh, shard_run

    spec = trmm(16)
    cfg = SamplerConfig(cls=8)
    a = engine.run(spec, cfg)
    b = shard_run(spec, cfg, mesh=default_mesh(4), window_accesses=1)
    assert a.noshare_dense.tolist() == b.noshare_dense.tolist()
    assert a.share_raw == b.share_raw


@pytest.mark.parametrize("n", [8, 13])
def test_symm_matches_oracle(n):
    # symm's k-loop has bound (0, 1): ZERO iterations at i=0 — the empty
    # bounded-window edge — plus a cross-row store C[k][j] and the diagonal
    # ref A[i][i]
    from pluss.models import symm

    spec = symm(n)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


def test_symm_shard_matches_engine():
    from pluss.models import symm
    from pluss.parallel.shard import default_mesh, shard_run

    spec = symm(16)
    cfg = SamplerConfig()
    a = engine.run(spec, cfg)
    b = shard_run(spec, cfg, mesh=default_mesh(4), window_accesses=1)
    assert a.noshare_dense.tolist() == b.noshare_dense.tolist()
    assert a.share_raw == b.share_raw


@pytest.mark.parametrize("n", [8, 13])
def test_correlation_matches_oracle(n):
    # four nests back-to-back mixing rectangular and triangular shapes;
    # cross-nest carried state through mean/stddev/data/corr
    from pluss.models import correlation

    spec = correlation(n)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


def test_correlation_shard_matches_engine():
    from pluss.models import correlation
    from pluss.parallel.shard import default_mesh, shard_run

    spec = correlation(16)
    cfg = SamplerConfig()
    a = engine.run(spec, cfg)
    b = shard_run(spec, cfg, mesh=default_mesh(4), window_accesses=1)
    assert a.noshare_dense.tolist() == b.noshare_dense.tolist()
    assert a.share_raw == b.share_raw


@pytest.mark.parametrize("n", [8, 13])
def test_covariance_matches_oracle(n):
    # covariance: varying START and varying TRIP on the same loop
    # (j = i .. n-1), plus the symmetric cross-row store cov[j][i]
    from pluss.models import covariance

    spec = covariance(n)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


def test_covariance_shard_matches_engine():
    from pluss.models import covariance
    from pluss.parallel.shard import default_mesh, shard_run

    spec = covariance(16)
    cfg = SamplerConfig()
    a = engine.run(spec, cfg)
    b = shard_run(spec, cfg, mesh=default_mesh(4), window_accesses=1)
    assert a.noshare_dense.tolist() == b.noshare_dense.tolist()
    assert a.share_raw == b.share_raw


def test_start_coef_fixed_trip_excluded_from_templates():
    # regression (code-review r2): a varying-START loop with a FIXED trip
    # has n1 == 0 and used to slip through the template gate with wrong
    # addresses; the nest must take the sort path and match the oracle
    # at a template-eligible size with multiple windows
    from pluss.engine import plan

    n = 64
    nest = Loop(trip=n, body=(
        Loop(trip=4, start_coef=1, body=(
            Ref("X0", "X", addr_terms=((1, 1),)),
        )),
    ))
    spec = LoopNestSpec(name="varstart",
                        arrays=(("X", n + 4),), nests=(nest,))
    cfg = SamplerConfig(cls=8)
    assert plan(spec, cfg).nests[0].tpl is None, "template must be skipped"
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))
    assert_matches_oracle(spec, cfg,
                          engine.run(spec, cfg, window_accesses=32))


def test_start_coef_root_rejected():
    with pytest.raises(ValueError, match="outermost"):
        flatten_nest(Loop(trip=4, start_coef=1, body=(
            Ref("X0", "X", addr_terms=((0, 4),)),
        )))


def test_canceling_sibling_bounds():
    # soak-found regression: two sibling bounded loops with OPPOSITE slopes
    # leave the net body slope n1 == 0, but refs after the first sibling
    # still have nonzero offset_k — the nest must take the clock-table path
    # (and never the template), keyed on nest_has_bounds, not on n1
    from pluss.engine import plan
    from pluss.parallel.shard import default_mesh, shard_run

    spec = LoopNestSpec(name="cancel", arrays=(("X", 1),), nests=(
        Loop(trip=2, body=(
            Loop(trip=2, bound_coef=(1, 1),
                 body=(Ref("R0", "X", addr_terms=()),)),
            Loop(trip=2, bound_coef=(1, -1),
                 body=(Ref("R1", "X", addr_terms=()),)),
        )),
    ))
    cfg = SamplerConfig(thread_num=1, chunk_size=1, ds=8, cls=8)
    pl = plan(spec, cfg)
    assert pl.nests[0].clock is not None, "clock path must activate"
    assert pl.nests[0].tpl is None, "template must be skipped"
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))
    from tests.conftest import require_shard_backend

    require_shard_backend()  # the shard half needs a usable shard_map
    o = OracleSampler(spec, cfg).run()
    for nd in (2, 8):
        s = shard_run(spec, cfg, mesh=default_mesh(nd))
        assert s.noshare_dict(0) == o.noshare[0], f"shard{nd}"


def test_lower_triangular_bound():
    # b < 0: j runs n-k iterations (the other triangle); engine == oracle
    n = 8
    nest = Loop(trip=n, body=(
        Loop(trip=n, bound_coef=(n, -1), body=(
            Ref("X0", "X", addr_terms=((0, n), (1, 1))),
        )),
    ))
    spec = LoopNestSpec(name="lowtri", arrays=(("X", n * n),), nests=(nest,))
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


def test_native_rejects_what_engine_rejects():
    # spec_tokens runs flatten_nest validation: the native twin must not
    # silently interpret an invalid spec rectangularly (code-review r2)
    bad = LoopNestSpec(
        name="bad", arrays=(("X", 16),),
        nests=(Loop(trip=4, bound_coef=(1, 1), body=(
            Ref("X0", "X", addr_terms=((0, 4),)),
        )),),
    )
    with pytest.raises(ValueError, match="outermost"):
        native.spec_tokens(bad)


def test_validation_errors():
    with pytest.raises(ValueError, match="outermost"):
        flatten_nest(Loop(trip=4, bound_coef=(1, 1), body=(
            Ref("X0", "X", addr_terms=((0, 4),)),
        )))
    # bounded-inside-bounded no longer rejects: it dispatches to the quad
    # flatten (round 4 — spec.nest_is_quad); the AFFINE accounting alone
    # still refuses it, which loop_size_affine pins
    from pluss.spec import loop_size_affine, nest_is_quad

    nested = Loop(trip=4, body=(
        Loop(trip=4, bound_coef=(1, 1), body=(
            Loop(trip=4, bound_coef=(1, 1), body=(
                Ref("X0", "X", addr_terms=((0, 4),)),
            )),
        )),
    ))
    assert nest_is_quad(nested)
    assert len(flatten_nest(nested)) == 1
    with pytest.raises(ValueError, match="nest inside|quad"):
        loop_size_affine(nested.body[0])
    with pytest.raises(ValueError, match="leaves"):
        # bound exceeds the declared static trip at the last parallel index
        flatten_nest(Loop(trip=4, body=(
            Loop(trip=2, bound_coef=(1, 1), body=(
                Ref("X0", "X", addr_terms=((0, 4),)),
            )),
        )))


def test_tri_buckets_engage_and_match_oracle():
    """Size-bucketed triangular segments: multi-window tri nests split into
    buckets with per-bucket static trips (engine._tri_buckets); results
    must stay oracle-exact across every triangular family."""
    from pluss import engine
    from pluss.models import REGISTRY
    from tests.test_engine import assert_matches_oracle

    for name in ("syrk_tri", "trmm", "symm", "covariance"):
        spec = REGISTRY[name](64)
        pl = engine.plan(spec, engine.DEFAULT, window_accesses=1)
        # PER NEST: a tri nest is either emptied by the closed-form groups
        # (rowpriv/sweepgroup — nothing left to bucket) or bucketed
        checked = 0
        for n_ in pl.nests:
            if n_.clock is None:
                continue
            checked += 1
            assert (not n_.refs) or (
                n_.tri_buckets and len(n_.tri_buckets) > 1), \
                f"{name}: buckets missing on a sorting tri nest"
        assert checked, f"{name}: no tri nest found"
        assert_matches_oracle(spec, engine.DEFAULT, window_accesses=1)


def test_tri_buckets_shrink_trips(monkeypatch, request):
    from pluss import engine
    from pluss.models import syrk_triangular

    # closed-form groups off: syrk_tri must fall back to bucketed sort
    monkeypatch.setenv("PLUSS_NO_ROWPRIV", "1")
    monkeypatch.setenv("PLUSS_NO_SWEEPGROUP", "1")
    engine.compiled.cache_clear()
    request.addfinalizer(engine.compiled.cache_clear)
    pl = engine.plan(syrk_triangular(64), engine.DEFAULT, window_accesses=1)
    np_ = pl.nests[0]
    assert np_.tri_buckets is not None
    # first bucket's bounded levels must be strictly tighter than the last's
    first = np_.tri_buckets[0][1][0].trips
    last = np_.tri_buckets[-1][1][0].trips
    assert first != last and all(a <= b for a, b in zip(first, last))
