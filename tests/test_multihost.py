"""Multi-process (DCN-model) smoke test: 2 local processes, one shard_run.

The reference has no distributed backend (SURVEY.md §2: shared memory +
locks); this framework's claim is that multi-host is a *configuration* of the
collectives-only shard backend.  This test proves the claim for real: two
OS processes initialize ``jax.distributed`` against a local coordinator,
form one global 8-device CPU mesh (4 virtual devices each), run
``shard_run`` in SPMD, and the coordinator's result must equal the
single-process engine run bit for bit.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import json, sys
from pluss.utils.platform import force_cpu
force_cpu(4)  # 4 virtual CPU devices per process -> 8 global
from pluss.parallel import multihost

port, pid, out_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=2, process_id=pid)
import jax
assert multihost.process_count() == 2
assert jax.device_count() == 8 and len(jax.local_devices()) == 4

from pluss.config import SamplerConfig
from pluss.models import gemm
from pluss.parallel.shard import shard_run

mesh = multihost.global_mesh()
assert mesh.devices.size == 8
res = shard_run(gemm(16), SamplerConfig(cls=8), mesh=mesh,
                window_accesses=1)  # forces S>1 sub-windows across hosts
if multihost.is_coordinator():
    json.dump({
        "count": res.max_iteration_count,
        "hist": res.noshare_dense.tolist(),
        "share": [{str(k): v for k, v in d.items()} for d in res.share_raw],
    }, open(out_path, "w"))
"""


@pytest.mark.slow
def test_two_process_shard_run_matches_engine(tmp_path):
    port = socket.socket()
    port.bind(("127.0.0.1", 0))
    portno = port.getsockname()[1]
    port.close()

    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    out = tmp_path / "res.json"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "JAX_ENABLE_X64": "1",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    # workers log to FILES: draining two interdependent SPMD processes
    # through pipes sequentially can deadlock on a full pipe buffer
    logs = [tmp_path / f"worker{i}.log" for i in range(2)]
    handles: list = []
    procs: list = []
    try:
        # spawn INSIDE the try: a failure launching worker 1 must still
        # kill worker 0 and close its log handle
        for i in range(2):
            handles.append(open(logs[i], "w"))
            procs.append(subprocess.Popen(
                [sys.executable, str(script), str(portno), str(i), str(out)],
                env=env, stdout=handles[i], stderr=subprocess.STDOUT,
            ))
        for p, lg in zip(procs, logs):
            p.wait(timeout=600)
            if p.returncode != 0 and "Multiprocess computations aren't " \
                    "implemented on the CPU backend" in lg.read_text():
                # environment guard: some jax versions cannot EXECUTE
                # multi-process SPMD on CPU at all (bring-up still works —
                # tests/test_resilience.py covers that path); skip with
                # the reason instead of failing on a missing capability
                pytest.skip("multi-process CPU execution unsupported by "
                            "this jax build")
            assert p.returncode == 0, \
                f"worker failed:\n{lg.read_text()[-2000:]}"
    finally:
        # a coordinator hang must not orphan the other jax.distributed
        # worker past the test run; per-process errors must not mask the
        # original failure or skip the remaining kills
        try:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    try:
                        p.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        pass
        finally:
            for h in handles:
                h.close()
    got = json.load(open(out))

    from pluss.config import SamplerConfig
    from pluss.engine import run
    from pluss.models import gemm

    ref = run(gemm(16), SamplerConfig(cls=8))
    assert got["count"] == ref.max_iteration_count
    assert got["hist"] == ref.noshare_dense.tolist()
    assert got["share"] == [
        {str(k): v for k, v in d.items()} for d in ref.share_raw
    ]
