"""d24v wire codec: host encode ≡ device decode, adversarial patterns.

The compressed trace wire (pluss/ops/wirecodec.py) must round-trip every
id pattern bit-exactly — the streamed replay's histograms are pinned
bit-identical to the u64 path, so a single mis-decoded id anywhere would
fail the property suite loudly.  This file hits the codec directly at
its edge cases: block-width boundaries, raw/delta mode flips, the
cross-block carry reset-scan, ragged tails, and the format's ceilings.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from pluss.ops import wirecodec as wc


def roundtrip(ids: np.ndarray) -> np.ndarray:
    payload, wm = wc.encode_d24v(ids)
    assert payload.nbytes == wc.pad_len(wc.used_bytes(wm))
    assert payload.nbytes % 4 == 0       # u32-word decode alignment
    dec = np.asarray(wc.decode_d24v(jnp.asarray(payload), jnp.asarray(wm)))
    assert dec.shape[0] % wc.BLOCK == 0  # whole blocks out
    return dec[:len(ids)]


PATTERNS = {
    "random24": lambda rng: rng.integers(0, 1 << 24, 5000, dtype=np.int32),
    "random16": lambda rng: rng.integers(0, 1 << 16, 4096, dtype=np.int32),
    "sequential": lambda rng: np.arange(3000, dtype=np.int32),
    # a scan high in a big table: global deltas keep it tiny even though
    # every id needs 23 bits raw
    "seq_high": lambda rng: (np.arange(5000, dtype=np.int32) % 4096)
    + (1 << 22),
    "constant": lambda rng: np.full(2500, 1234567, np.int32),
    "zeros": lambda rng: np.zeros(700, np.int32),
    "single": lambda rng: np.array([7], np.int32),
    "extremes": lambda rng: np.array(
        [0, (1 << 24) - 1, 1, (1 << 24) - 2] * 700, np.int32),
    # alternating noisy (raw-mode) and sequential (delta-mode) blocks:
    # the decoder's cross-block carry must survive every reset
    "mode_flips": lambda rng: np.concatenate([
        rng.integers(0, 1 << 23, wc.BLOCK, dtype=np.int32)
        if i % 2 else np.arange(wc.BLOCK, dtype=np.int32) + (1 << 20)
        for i in range(12)]),
    # every nibble width in one batch: per-block maxima at each 4-bit
    # boundary (1, 2^4-1, 2^8-1, ..., 2^24-1) in raw mode
    "width_ladder": lambda rng: np.concatenate([
        np.minimum(rng.integers(0, 1 << min(4 * k, 24), wc.BLOCK,
                                dtype=np.int64),
                   (1 << 24) - 1).astype(np.int32)
        for k in range(7)]),
}


@pytest.mark.parametrize("name", sorted(PATTERNS))
def test_roundtrip_patterns(name):
    rng = np.random.default_rng(hash(name) % 2**32)
    ids = PATTERNS[name](rng)
    np.testing.assert_array_equal(roundtrip(ids), ids)


@pytest.mark.parametrize("seed", range(6))
def test_roundtrip_random_ragged(seed):
    """Random lengths straddling block boundaries (the encoder pads with
    the last id; the decoder's tail must still slice back exactly)."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 4 * wc.BLOCK + 3))
    hi = int(rng.integers(1, 24))
    ids = rng.integers(0, 1 << hi, n, dtype=np.int32)
    np.testing.assert_array_equal(roundtrip(ids), ids)


def test_sequential_compresses_well():
    """The point of the format: a sequential scan packs far under the
    3 B/ref u24 wire (deltas of 1 are one nibble + headers)."""
    ids = np.arange(16 * wc.BLOCK, dtype=np.int32) + (1 << 20)
    _, wm = wc.encode_d24v(ids)
    assert wc.used_bytes(wm) <= len(ids)   # <= 1 B/ref vs 3 B/ref u24


def test_random_never_worse_than_raw_width():
    """Uniform noise defeats delta coding; raw mode must cap the cost at
    the plain pack's nibble-rounded width."""
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 1 << 24, 8 * wc.BLOCK, dtype=np.int32)
    _, wm = wc.encode_d24v(ids)
    assert wc.used_bytes(wm) <= 3 * len(ids)


def test_rejects_out_of_range_and_empty():
    with pytest.raises(ValueError, match="2\\*\\*24"):
        wc.encode_d24v(np.array([1 << 24], np.int32))
    with pytest.raises(ValueError, match="2\\*\\*24"):
        wc.encode_d24v(np.array([-1], np.int32))
    with pytest.raises(ValueError, match="empty"):
        wc.encode_d24v(np.array([], np.int32))


def test_pad_len_quantization_is_bounded():
    """Payload padding must stay within ~12.5% + alignment (it is wire
    overhead) while collapsing lengths to few distinct shapes."""
    import random

    random.seed(5)
    for _ in range(200):
        nbytes = random.randint(0, 1 << 27)
        padded = wc.pad_len(nbytes)
        assert padded >= nbytes + 4          # guard word always fits
        assert padded % 4 == 0
        assert padded <= max(nbytes * 1.14 + 4096, 8192)
    # shape stability: nearby lengths share a padded size
    assert len({wc.pad_len(x) for x in range(1 << 20, (1 << 20) + 5000)}) \
        <= 2


def test_used_bytes_matches_encoder():
    rng = np.random.default_rng(9)
    ids = rng.integers(0, 1 << 20, 3 * wc.BLOCK + 17, dtype=np.int32)
    payload, wm = wc.encode_d24v(ids)
    used = wc.used_bytes(wm)
    # everything past `used` is pure padding the encoder never wrote
    assert not payload[used:].any()
