"""End-to-end `pluss serve` daemon tests: in-process servers on unix
sockets / TCP, mixed-request serving bit-compared against solo runs,
shared-dispatch coalescing, typed shedding, per-request resilience
isolation (a degraded request never corrupts a neighbor), deadlines,
drain-and-stop, the serve SLO telemetry block, and the heartbeat
long-poll exporter."""

import json
import threading
import time

import numpy as np
import pytest

import tests.conftest  # noqa: F401  (CPU platform + x64)
from pluss import cri, engine, mrc, obs
from pluss import trace as trace_mod
from pluss.config import SamplerConfig
from pluss.models import REGISTRY
from pluss.resilience import FaultPlan
from pluss.resilience import faults
from pluss.serve import Client, ServeConfig, Server


@pytest.fixture
def server_factory(tmp_path):
    """Builds in-process servers on throwaway unix sockets; always shuts
    them down (daemon threads must not leak across tests)."""
    servers = []
    counter = [0]

    def build(**cfg_kw) -> Server:
        counter[0] += 1
        sock = str(tmp_path / f"s{counter[0]}.sock")
        srv = Server(socket_path=sock, config=ServeConfig(**cfg_kw))
        srv.start()
        servers.append(srv)
        return srv

    yield build
    for srv in servers:
        srv.shutdown(drain_timeout_s=30)


@pytest.fixture
def clean_faults():
    yield
    faults.install(None)


def solo_spec(model, n, threads=2, chunk=2):
    cfg = SamplerConfig(thread_num=threads, chunk_size=chunk)
    res = engine.run(REGISTRY[model](n), cfg)
    ri = cri.distribute(res.noshare_list(), res.share_list(),
                        cfg.thread_num)
    return {"mrc": [[int(c), float(m)]
                    for c, m in mrc.dedup_lines(mrc.aet_mrc(ri, cfg))],
            "histogram": {str(int(k)): float(v)
                          for k, v in sorted(ri.items())}}


# ---------------------------------------------------------------------------
# end-to-end correctness


def test_mixed_requests_match_solo(server_factory, tmp_path):
    srv = server_factory(max_batch=8, max_delay_ms=10)
    trace_path = tmp_path / "refs.bin"
    rng = np.random.default_rng(3)
    rng.integers(0, 512, 4096).astype("<u8").tofile(trace_path)
    with Client(srv.socket_path) as c:
        rs = c.request_many([
            {"model": "gemm", "n": 16, "threads": 2, "chunk": 2,
             "output": "both"},
            {"model": "mvt", "n": 12, "threads": 2, "chunk": 2,
             "output": "both"},
            {"trace": str(trace_path), "output": "both"},
        ])
    assert all(r["ok"] for r in rs)
    assert rs[0]["mrc"] == solo_spec("gemm", 16)["mrc"]
    assert rs[0]["histogram"] == solo_spec("gemm", 16)["histogram"]
    assert rs[1]["mrc"] == solo_spec("mvt", 12)["mrc"]
    # trace solo
    rep = trace_mod.replay_file(str(trace_path), "u64", cls=64)
    cfg = SamplerConfig()
    ri = rep.histogram()
    assert rs[2]["histogram"] == {str(int(k)): float(v)
                                  for k, v in sorted(ri.items())}
    assert rs[2]["mrc"] == [[int(c), float(m)] for c, m in
                            mrc.dedup_lines(mrc.aet_mrc(ri, cfg))]
    assert rs[2]["refs"] == 4096


def test_inline_spec_request(server_factory):
    from pluss.serve.protocol import spec_to_json

    srv = server_factory(max_batch=4)
    doc = spec_to_json(REGISTRY["gemm"](13))
    doc["name"] = "tenant13"
    with Client(srv.socket_path) as c:
        r = c.request({"spec": doc, "threads": 2, "chunk": 2,
                       "output": "both"})
    assert r["ok"] and r["model"] == "tenant13"
    assert r["histogram"] == solo_spec("gemm", 13)["histogram"]


def test_coalescing_shares_one_dispatch(server_factory):
    """Identical requests queued behind a hold come back from ONE shared
    dispatch (``batched`` > 1), bit-identical to each other."""
    srv = server_factory(max_batch=8, max_delay_ms=10, max_queue=32)
    with Client(srv.socket_path) as c:
        hold = c.send({"sleep_ms": 400})
        time.sleep(0.1)   # the hold must reach the device loop first
        ids = [c.send({"model": "gemm", "n": 16, "threads": 2,
                       "chunk": 2}) for _ in range(5)]
        rs = [c.recv(i) for i in ids]
        c.recv(hold)
    assert all(r["ok"] for r in rs)
    assert {r["batched"] for r in rs} == {5}, \
        "queued compatible requests must coalesce onto one dispatch"
    assert len({json.dumps(r["mrc"]) for r in rs}) == 1


def test_incompatible_requests_not_coalesced(server_factory):
    srv = server_factory(max_batch=8, max_delay_ms=5, max_queue=32)
    with Client(srv.socket_path) as c:
        hold = c.send({"sleep_ms": 300})
        time.sleep(0.1)
        a = c.send({"model": "gemm", "n": 16, "threads": 2, "chunk": 2})
        b = c.send({"model": "gemm", "n": 16, "threads": 4, "chunk": 2})
        ra, rb = c.recv(a), c.recv(b)
        c.recv(hold)
    assert ra["ok"] and rb["ok"]
    assert ra["batched"] == 1 and rb["batched"] == 1, \
        "different schedules must not share a dispatch"


# ---------------------------------------------------------------------------
# admission / shedding / deadlines


def test_overload_sheds_with_typed_error(server_factory):
    srv = server_factory(max_queue=2, max_batch=1, max_delay_ms=0)
    with Client(srv.socket_path) as c:
        hold = c.send({"sleep_ms": 500})
        time.sleep(0.1)
        ids = [c.send({"model": "gemm", "n": 16, "threads": 2,
                       "chunk": 2}) for _ in range(6)]
        rs = [c.recv(i) for i in ids]
        c.recv(hold)
    shed = [r for r in rs if not r["ok"]]
    served = [r for r in rs if r["ok"]]
    assert shed, "a burst past max_queue must shed"
    assert all(r["error"]["type"] == "Overloaded" and
               r["error"]["retryable"] for r in shed)
    assert len(served) <= 2 + 1   # at most the queue depth (+1 in-flight)


def test_deadline_exceeded_while_queued(server_factory):
    srv = server_factory(max_queue=8, max_batch=1, max_delay_ms=0)
    with Client(srv.socket_path) as c:
        hold = c.send({"sleep_ms": 400})
        time.sleep(0.1)
        rid = c.send({"model": "gemm", "n": 16, "threads": 2,
                      "chunk": 2, "deadline_ms": 50})
        r = c.recv(rid)
        c.recv(hold)
    assert not r["ok"]
    assert r["error"]["type"] == "DeadlineExceeded"


def test_invalid_requests_get_typed_errors(server_factory):
    srv = server_factory()
    with Client(srv.socket_path) as c:
        r = c.request({"model": "no_such_model", "id": "x"})
        assert not r["ok"] and r["error"]["type"] == "InvalidRequest"
        # raw garbage on the wire
        c._sock.sendall(b"this is not json\n")
        raw = json.loads(c._rfile.readline())
        assert not raw["ok"] and raw["error"]["type"] == "InvalidRequest"
        # the connection survives both
        assert c.request({"op": "ping"})["ok"]


def test_analyzer_gate_rejects_with_diagnostics(server_factory):
    srv = server_factory()
    bad = {"name": "oob", "arrays": [["A", 1]],
           "nests": [{"trip": 8, "body": [
               {"name": "A1", "array": "A", "addr_terms": [[0, 1]]}]}]}
    with Client(srv.socket_path) as c:
        r = c.request({"spec": bad, "threads": 2})
    assert not r["ok"] and r["error"]["type"] == "InvalidRequest"
    assert r["error"]["diagnostics"], "analyzer findings must reach the client"


# ---------------------------------------------------------------------------
# per-request resilience isolation


def test_degraded_request_isolated_from_neighbors(server_factory,
                                                  clean_faults):
    """The acceptance pin: an injected per-request fault rides the serve
    ladder; the degraded request AND its concurrent neighbors all come
    back bit-identical to solo runs."""
    solo_a = solo_spec("gemm", 16)
    solo_b = solo_spec("mvt", 12)
    srv = server_factory(max_batch=8, max_delay_ms=5, max_queue=32)
    faults.install(FaultPlan.parse("oom@1"))
    try:
        with Client(srv.socket_path) as c:
            hold = c.send({"sleep_ms": 300})
            time.sleep(0.1)
            ids_a = [c.send({"model": "gemm", "n": 16, "threads": 2,
                             "chunk": 2, "output": "both"})
                     for _ in range(2)]
            id_b = c.send({"model": "mvt", "n": 12, "threads": 2,
                           "chunk": 2, "output": "both"})
            rs_a = [c.recv(i) for i in ids_a]
            rb = c.recv(id_b)
            c.recv(hold)
    finally:
        faults.install(None)
    assert all(r["ok"] for r in rs_a) and rb["ok"]
    # the first dispatched batch ate the injected OOM and degraded
    assert any(r.get("degradations") for r in rs_a + [rb]), \
        "the injected fault must surface as a ladder degradation stamp"
    for r in rs_a:
        assert r["histogram"] == solo_a["histogram"], \
            "a degraded batch must stay bit-identical to the solo run"
        assert r["mrc"] == solo_a["mrc"]
    assert rb["histogram"] == solo_b["histogram"], \
        "a neighbor of a degraded request must be untouched"
    assert rb["mrc"] == solo_b["mrc"]


def test_serve_ladder_never_pins_cpu():
    """The serve rung set must exclude the process-pinning cpu_fallback
    (one tenant's failure must not degrade every later tenant)."""
    from pluss.resilience.ladder import LADDER, SERVE_LADDER
    from pluss.serve.server import SERVE_TRACE_LADDER

    assert "cpu_fallback" not in SERVE_LADDER
    assert "cpu_fallback" not in SERVE_TRACE_LADDER
    assert set(SERVE_LADDER) <= set(LADDER), \
        "serve rungs must be known rungs of the default ladder"


# ---------------------------------------------------------------------------
# control surface, drain, TCP


def test_control_ops(server_factory):
    srv = server_factory()
    with Client(srv.socket_path) as c:
        assert c.request({"op": "ping"})["ok"]
        st = c.request({"op": "stats"})
        assert st["ok"] and "queue_depth" in st
        r = c.request({"op": "nope"})
        assert not r["ok"] and r["error"]["type"] == "InvalidRequest"


def test_drain_answers_queued_then_stops(server_factory):
    srv = server_factory(max_batch=1, max_delay_ms=0, max_queue=16)
    with Client(srv.socket_path) as c:
        hold = c.send({"sleep_ms": 300})
        time.sleep(0.1)
        rid = c.send({"model": "gemm", "n": 16, "threads": 2, "chunk": 2})
        time.sleep(0.1)   # the request must be QUEUED before the drain
        t = threading.Thread(target=srv.shutdown, daemon=True)
        t.start()
        r = c.recv(rid)       # queued work is answered during the drain
        c.recv(hold)
        t.join(timeout=30)
    assert r["ok"], "drain must answer queued requests, not drop them"
    assert srv._drained.is_set()
    srv.shutdown()   # idempotent


def test_tcp_port_mode():
    srv = Server(port=0, config=ServeConfig(max_batch=2))
    srv.start()
    try:
        assert srv.port != 0
        with Client(f"127.0.0.1:{srv.port}") as c:
            assert c.request({"op": "ping"})["ok"]
            r = c.request({"model": "gemm", "n": 13, "threads": 2,
                           "chunk": 2})
            assert r["ok"] and r["mrc"]
    finally:
        srv.shutdown()


def test_server_ctor_validation(tmp_path):
    with pytest.raises(ValueError):
        Server()
    with pytest.raises(ValueError):
        Server(socket_path=str(tmp_path / "x.sock"), port=1234)


# ---------------------------------------------------------------------------
# SLO telemetry + exporters


def test_serve_slo_telemetry_block(server_factory, tmp_path):
    """A served stream carries the serve counters/gauges, passes the
    schema check, and renders the serve SLO block in `pluss stats`."""
    import io

    from pluss.obs import stats as stats_mod

    sink = tmp_path / "tel.jsonl"
    obs.configure(str(sink))
    try:
        srv = server_factory(max_batch=8, max_delay_ms=5)
        with Client(srv.socket_path) as c:
            for _ in range(3):
                assert c.request({"model": "gemm", "n": 16, "threads": 2,
                                  "chunk": 2})["ok"]
        # quiesce BEFORE closing the sink: spans record at exit, so the
        # last serve.batch span must close before the end record lands
        srv.shutdown()
        obs.flush_metrics()
        cs, gs = obs.counters(), obs.gauges()
    finally:
        obs.shutdown()
    assert cs["serve.requests"] == 3 and cs["serve.ok"] == 3
    assert cs["serve.batches"] >= 1
    assert "serve.p50_ms" in gs and "serve.queue_depth" in gs
    records, problems, _ = stats_mod.load(str(sink))
    assert not problems, problems
    out = io.StringIO()
    stats_mod.render(records, out)
    text = out.getvalue()
    assert "serve SLO:" in text
    assert "latency p50 / p99" in text
    assert "batches dispatched" in text


def test_serve_breakdown_absent_without_serve_counters():
    from pluss.obs.stats import serve_breakdown

    assert serve_breakdown({"trace.h2d_s": 1.0}, {}) == []


def test_heartbeat_longpoll_exporter(tmp_path):
    """The PR-5 follow-up: heartbeat_age_s gauges land in the Prometheus
    textfile on a timer from a RUNNING process, not only at shutdown."""
    from pluss.parallel import multihost

    hb = tmp_path / "hb"
    hb.mkdir()
    (hb / "hb.0.json").write_text("{}")
    prom = tmp_path / "prom.txt"
    obs.configure(str(tmp_path / "tel.jsonl"), prom_path=str(prom))
    try:
        stop = multihost.start_heartbeat_exporter(str(hb), 2,
                                                  interval_s=0.2)
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if prom.exists() and "heartbeat_age_s" in prom.read_text():
                    break
                time.sleep(0.1)
        finally:
            stop()
        text = prom.read_text()
        assert "pluss_multihost_heartbeat_age_s_0" in text, text[:400]
        # the missing worker 1 gauges -1 (scrapeably dead, not absent)
        assert "pluss_multihost_heartbeat_age_s_1 -1" in text
    finally:
        obs.shutdown()


def test_heartbeat_exporter_stop_is_idempotent(tmp_path):
    from pluss.parallel import multihost

    hb = tmp_path / "hb"
    hb.mkdir()
    stop = multihost.start_heartbeat_exporter(str(hb), 1, interval_s=0.2)
    stop()
    stop()


# ---------------------------------------------------------------------------
# the "source" request kind (PR 8): frontend -> analyzer gate -> shared
# dispatch, end-to-end through a live daemon


def _gemm_c(n: int) -> str:
    from pluss.frontend import polybench

    src = open(polybench.gemm_source_path()).read()
    return src.replace("#define N 128", f"#define N {n}")


def test_source_request_end_to_end(server_factory):
    srv = server_factory(max_batch=8, max_delay_ms=5)
    with Client(srv.socket_path) as c:
        r = c.request({"source": _gemm_c(16), "lang": "c",
                       "name": "gemm_src", "threads": 2, "chunk": 2,
                       "output": "both"})
    assert r["ok"], r
    assert r["model"] == "gemm_src"
    # the frontend-derived spec rides the EXISTING spec path: result
    # bit-identical to the registry model's solo run
    solo = solo_spec("gemm", 16)
    assert r["histogram"] == solo["histogram"]
    assert r["mrc"] == solo["mrc"]


def test_source_request_coalesces_with_model_request(server_factory):
    # a source-derived gemm and the registry gemm have equal specs ->
    # equal dispatch keys -> ONE shared dispatch serves both
    srv = server_factory(max_batch=8, max_delay_ms=200)
    src = _gemm_c(16)
    results = {}

    def one(key, req):
        with Client(srv.socket_path) as c:
            results[key] = c.request(req)

    # park a slow sleep first so the batcher lingers and both arrive
    with Client(srv.socket_path) as c:
        c.request({"sleep_ms": 150})
    ts = [threading.Thread(target=one, args=("src", {
              "source": src, "name": "gemm16", "threads": 2,
              "chunk": 2})),
          threading.Thread(target=one, args=("model", {
              "model": "gemm", "n": 16, "threads": 2, "chunk": 2}))]
    with Client(srv.socket_path) as c:
        hold = c.send({"sleep_ms": 300})
        for t in ts:
            t.start()
        time.sleep(0.1)
        for t in ts:
            t.join()
        c.recv(hold)
    assert results["src"]["ok"] and results["model"]["ok"]
    # both answered identically (the coalesce itself is timing-
    # dependent; bit-identity of the shared path is the contract)
    assert results["src"]["mrc"] == results["model"]["mrc"]


def test_source_request_rejection_with_findings(server_factory):
    srv = server_factory()
    bad = _gemm_c(8).replace("A[c0][c2]", "A[c0][c0 * c2]")
    with Client(srv.socket_path) as c:
        r = c.request({"source": bad, "lang": "c"})
        r2 = c.request({"source": _gemm_c(8), "lang": "py"})
    assert not r["ok"]
    assert r["error"]["type"] == "InvalidRequest"
    assert r["error"]["diagnostics"][0]["code"] == "PL601"
    assert not r2["ok"] and r2["error"]["type"] == "InvalidRequest"


def test_source_requests_counted_by_origin(server_factory, tmp_path):
    # the SLO counters key on the ingestion surface: a source request
    # executes as kind "spec" but counts serve.requests.source
    obs.configure(str(tmp_path / "tel.jsonl"))
    try:
        srv = server_factory()
        with Client(srv.socket_path) as c:
            assert c.request({"source": _gemm_c(8), "threads": 2,
                              "chunk": 2})["ok"]
            stats = c.request({"op": "stats"})
    finally:
        obs.shutdown()
    assert stats["counters"].get("serve.requests.source") == 1
    assert "serve.requests.spec" not in stats["counters"]
