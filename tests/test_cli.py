"""CLI + printer parity: block format, determinism, cross-backend agreement."""

import io as _io
import re

import pytest

from pluss import cli, cri, engine
from pluss.io import (
    NOSHARE_TITLE,
    RI_TITLE,
    SHARE_TITLE,
    acc_block,
    fmt_double,
    histogram_lines,
    merge_noshare,
    merge_share,
)
from pluss.models import gemm


def test_fmt_double_matches_cout_defaults():
    # std::cout << double prints 6 significant digits, scientific past ~1e6
    assert fmt_double(2127872.0) == "2.12787e+06"
    assert fmt_double(12288.0) == "12288"
    assert fmt_double(0.2527354) == "0.252735"
    assert fmt_double(1.0) == "1"


def test_histogram_lines_sorted_with_ratio():
    lines = list(histogram_lines("T", {4: 1.0, -1: 2.0, 2: 1.0}))
    assert lines[0] == "T"
    assert lines[1].startswith("-1,2,0.5")
    keys = [int(l.split(",")[0]) for l in lines[1:]]
    assert keys == sorted(keys)


@pytest.fixture(scope="module")
def gemm16():
    res = engine.run(gemm(16))
    ri = cri.distribute(res.noshare_list(), res.share_list(), 4)
    return res, ri


def test_acc_block_format(gemm16):
    res, ri = gemm16
    buf = _io.StringIO()
    acc_block("TPU VMAP", 0.1234567, res.noshare_list(), res.share_list(),
              ri, res.max_iteration_count, buf)
    lines = buf.getvalue().splitlines()
    assert lines[0] == "TPU VMAP: 0.123457"
    assert NOSHARE_TITLE in lines and SHARE_TITLE in lines and RI_TITLE in lines
    assert lines[-3] == "max iteration traversed"
    assert lines[-2] == str(res.max_iteration_count)
    assert lines[-1] == ""
    # every histogram line is key,count,ratio
    for ln in lines[1:-3]:
        if ln and not ln.startswith("Start to dump"):
            assert re.fullmatch(r"-?\d+,[^,]+,[^,]+", ln), ln


def test_acc_blocks_agree_across_backends(capsys):
    cli.main(["acc", "--n", "16", "--backends", "vmap,seq"])
    blocks = capsys.readouterr().out.strip().split("\n\n")
    assert len(blocks) == 2
    # strip the timing banner; everything else must be identical (the
    # reference's differential acc criterion, SURVEY.md §4)
    bodies = ["\n".join(b.splitlines()[1:]) for b in blocks]
    assert bodies[0] == bodies[1]
    assert "max iteration traversed" in bodies[0]


def test_speed_mode_block(capsys):
    cli.main(["speed", "--n", "16", "--backends", "vmap", "--reps", "2"])
    out = capsys.readouterr().out
    assert len(re.findall(r"^TPU VMAP: \d+\.\d{6}$", out, re.M)) == 2


def test_mrc_mode(tmp_path, capsys):
    out = tmp_path / "m.csv"
    cli.main(["mrc", "--n", "16", "--backends", "vmap", "--out", str(out)])
    text = out.read_text().splitlines()
    assert text[0] == "miss ratio"
    assert text[1].startswith("0, 1")


def test_acc_block_with_pri(gemm16):
    from pluss.io import PRI_TITLE, merge_pri

    res, ri = gemm16
    buf = _io.StringIO()
    acc_block("TPU VMAP", 0.0, res.noshare_list(), res.share_list(), ri,
              res.max_iteration_count, buf, with_pri=True)
    assert PRI_TITLE in buf.getvalue()
    pri = merge_pri(res.noshare_list(), res.share_list())
    # pri = noshare keys plus raw share keys, counts preserved
    assert sum(pri.values()) == sum(merge_noshare(res.noshare_list()).values()) \
        + sum(merge_share(res.share_list()).values())


def test_merge_share_raw_keys(gemm16):
    res, _ = gemm16
    m = merge_share(res.share_list())
    assert all(k > 0 for k in m)  # raw reuse values, no -1, unbinned


def test_merge_noshare_has_cold_key(gemm16):
    res, _ = gemm16
    assert -1 in merge_noshare(res.noshare_list())


def test_trace_mode(tmp_path, capsys):
    import numpy as np

    from pluss import cli

    path = tmp_path / "t.bin"
    rng = np.random.default_rng(0)
    addrs = (rng.integers(0, 256, 5000) * 64).astype("<u8")
    addrs.tofile(path)
    out = tmp_path / "m.csv"
    cli.main(["trace", "--file", str(path), "--out", str(out), "--cpu"])
    got = capsys.readouterr().out
    assert "TPU TRACE:" in got and "Start to dump reuse time" in got
    assert f"5000 refs over" in got
    assert out.read_text().startswith("miss ratio")


def test_trace_mode_batch_windows_flag(tmp_path, capsys):
    # --batch-windows re-cuts the device batches; the histogram block must
    # be byte-identical to the default batching (partition invariance)
    import numpy as np

    from pluss import cli

    path = tmp_path / "t.bin"
    rng = np.random.default_rng(7)
    (rng.integers(0, 256, 5000) * 64).astype("<u8").tofile(path)
    outs = []
    for extra in ([], ["--batch-windows", "2"]):
        cli.main(["trace", "--file", str(path), "--cpu", "--window", "512",
                  "--out", str(tmp_path / "m.csv")] + extra)
        outs.append([l for l in capsys.readouterr().out.splitlines()
                     if not l.startswith("TPU TRACE:")])
    assert outs[0] == outs[1]


@pytest.mark.slow  # tier-1 keeps test_trace_mode; the sharded replay
# identity itself is pinned in test_trace.py
def test_trace_mode_shard_backend(tmp_path, capsys):
    # --backends shard routes trace mode through the device-sharded replay;
    # histogram lines must equal the streamed path's (table-slot diagnostic
    # aside — the two compaction routes size their tables differently)
    import numpy as np

    from pluss import cli

    path = tmp_path / "t.bin"
    rng = np.random.default_rng(4)
    (rng.integers(0, 512, 8000) * 64).astype("<u8").tofile(path)
    outs = []
    for be in ("vmap", "shard"):
        cli.main(["trace", "--file", str(path), "--cpu", "--backends", be,
                  "--out", str(tmp_path / f"m_{be}.csv")])
        outs.append([l for l in capsys.readouterr().out.splitlines()
                     if not l.startswith("TPU") and "lines" not in l])
    assert outs[0] == outs[1]
    assert (tmp_path / "m_vmap.csv").read_text() == \
        (tmp_path / "m_shard.csv").read_text()


def test_cli_window_and_start_point(capsys):
    from pluss import cli

    cli.main(["acc", "--cpu", "--n", "32", "--backends", "vmap",
              "--window", "512", "--start-point", "16"])
    got = capsys.readouterr().out
    # iteration 16 sits in round 1: every thread skips round 0 entirely
    total = int(got.strip().splitlines()[-1])
    assert 0 < total < 32 * 32 * (2 + 4 * 32)
    # and the count matches the engine with the same options
    from pluss import engine
    from pluss.models import gemm

    want = engine.run(gemm(32), start_point=16, window_accesses=512)
    assert total == want.max_iteration_count
