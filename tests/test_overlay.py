"""Interleave overlay (pluss.overlay): eligibility, exactness, fallback.

The overlay replaces the device sort for mixed-coefficient arrays (syrk's
A[i][k] / A[j][k] pair) with per-group templates + closed-form collision
corrections.  These tests pin: (a) the overlay actually engages for syrk,
(b) engine results are bit-identical with it on and off, (c) the plan-time
brute-force verifier catches a corrupted algebra, (d) ineligible shapes
fall back silently.
"""

import dataclasses

import numpy as np
import pytest

from pluss import engine
from pluss.config import SamplerConfig
from pluss.models import syrk
from pluss.sched import ChunkSchedule
from pluss.spec import flatten_nest, nest_iteration_size
from pluss import overlay as ovm
from tests.oracle import OracleSampler


def _overlay_arrays(pl):
    return [ov.array for np_ in pl.nests for ov in np_.overlays]


def _build(n, cfg, W=1):
    spec = syrk(n)
    nest = spec.nests[0]
    sched = ChunkSchedule(cfg.chunk_size, nest.trip, nest.start, nest.step,
                          cfg.thread_num)
    refs = [fr for fr in flatten_nest(nest) if fr.ref.array == "A"]
    ov = ovm.build_overlay("A", refs, cfg, sched, spec, W, 0,
                           nest_iteration_size(nest))
    return ov, sched


def test_overlay_engages_for_syrk():
    pl = engine.plan(syrk(32), SamplerConfig())
    assert _overlay_arrays(pl) == ["A"]
    # the overlaid array leaves the in-ultra sort stream entirely
    assert pl.nests[0].var_refs_novl == ()
    # ... but stays in var_refs for the shard backend and sort windows
    assert {fr.ref.array for fr in pl.nests[0].var_refs} == {"A"}


def test_overlay_off_matches_overlay_on(monkeypatch):
    spec, cfg = syrk(32), SamplerConfig()
    on = engine.run(spec, cfg)
    engine.compiled.cache_clear()
    monkeypatch.setenv("PLUSS_NO_OVERLAY", "1")
    assert _overlay_arrays(engine.plan(spec, cfg)) == []
    off = engine.run(spec, cfg)
    engine.compiled.cache_clear()  # don't leak the no-overlay executable
    assert np.array_equal(on.noshare_dense, off.noshare_dense)
    assert on.share_raw == off.share_raw
    assert on.max_iteration_count == off.max_iteration_count


def test_overlay_matches_oracle_seq_backend():
    spec, cfg = syrk(32), SamplerConfig()
    r = engine.run(spec, cfg, backend="seq")
    o = OracleSampler(spec, cfg).run()
    assert r.max_iteration_count == o.max_iteration_count
    for t in range(cfg.thread_num):
        assert r.noshare_dict(t) == o.noshare[t]
        assert r.share_dict(t) == \
            {k: dict(v) for k, v in o.share[t].items() if v}


@pytest.mark.parametrize("n,cfg,W", [
    (16, SamplerConfig(cls=8), 1),
    (32, SamplerConfig(), 2),
    (24, SamplerConfig(thread_num=3, chunk_size=2), 2),
    (64, SamplerConfig(thread_num=8, chunk_size=1), 1),
])
def test_verifier_exhaustive(n, cfg, W):
    import itertools

    ov, sched = _build(n, cfg, W)
    assert ov is not None
    rounds = -(-sched.n_chunks // cfg.thread_num)
    NW = rounds // W
    assert NW * W == rounds
    pairs = set(itertools.product(range(cfg.thread_num), range(NW)))
    assert ovm.verify_overlay(ov, cfg, sched, NW, pairs)


def test_verifier_catches_corruption(capsys):
    ov, sched = _build(32, SamplerConfig(), 1)
    bad = dataclasses.replace(ov, d_off=ov.d_off + 1)  # shift D's clock
    assert not ovm.verify_overlay(bad, SamplerConfig(), sched, 1, {(0, 0)})
    assert "verification FAILED" in capsys.readouterr().err


def test_ineligible_shapes_fall_back():
    # fractional row shift: 20 elements/row * 8 B = 160 B, not a multiple
    # of the 64 B line — overlay must decline, engine must still be exact
    cfg = SamplerConfig()
    ov, _ = _build(20, cfg, 1)
    assert ov is None
    pl = engine.plan(syrk(20), cfg)
    assert _overlay_arrays(pl) == []


@pytest.mark.parametrize("n,T,CS,cls", [
    (16, 4, 4, 8),
    (24, 3, 4, 8),
    (32, 2, 8, 16),
    (48, 4, 2, 8),
    (64, 8, 2, 64),
    (40, 5, 4, 8),
])
def test_overlay_grid_matches_oracle(n, T, CS, cls):
    """Overlay-eligible (n, threads, chunk, line-size) grid: the overlay
    must ENGAGE (not silently fall back) and match the oracle exactly."""
    cfg = SamplerConfig(thread_num=T, chunk_size=CS, cls=cls)
    spec = syrk(n)
    pl = engine.plan(spec, cfg)
    assert _overlay_arrays(pl) == ["A"], "overlay unexpectedly ineligible"
    r = engine.run(spec, cfg)
    o = OracleSampler(spec, cfg).run()
    assert r.max_iteration_count == o.max_iteration_count
    for t in range(T):
        assert r.noshare_dict(t) == o.noshare[t], f"tid {t} noshare"
        assert r.share_dict(t) == \
            {k: dict(v) for k, v in o.share[t].items() if v}, f"tid {t} share"


def test_overlay_two_nest_carry():
    """Cross-nest carries: a second nest re-touching the overlaid array
    must see absolute carried positions (the nb-offset contract of
    overlay.device_window)."""
    from pluss.spec import Loop, LoopNestSpec, Ref
    from pluss.spec import share_span_formula

    n = 16
    span = share_span_formula(n)
    def a_nest():
        inner = Loop(trip=n, body=(
            Ref("A0", "A", addr_terms=((0, n), (2, 1))),
            Ref("A1", "A", addr_terms=((1, n), (2, 1)), share_span=span),
        ))
        return Loop(trip=n, body=(Loop(trip=n, body=(inner,)),))

    spec = LoopNestSpec(name="twice", arrays=(("A", n * n),),
                        nests=(a_nest(), a_nest()))
    cfg = SamplerConfig(cls=8)
    pl = engine.plan(spec, cfg)
    assert _overlay_arrays(pl) == ["A", "A"]
    r = engine.run(spec, cfg)
    o = OracleSampler(spec, cfg).run()
    assert r.max_iteration_count == o.max_iteration_count
    for t in range(cfg.thread_num):
        assert r.noshare_dict(t) == o.noshare[t], f"tid {t} noshare"
        assert r.share_dict(t) == \
            {k: dict(v) for k, v in o.share[t].items() if v}, f"tid {t} share"


def test_syr2k_double_overlay_matches_oracle():
    """syr2k: BOTH operand arrays get overlays in one nest (A and B each
    carry the moving/sweeping pair); exact vs oracle, 21st model family."""
    from pluss.models import syr2k
    from tests.test_engine import assert_matches_oracle

    cfg = SamplerConfig()
    spec = syr2k(32)
    pl = engine.plan(spec, cfg)
    assert sorted(_overlay_arrays(pl)) == ["A", "B"]
    assert_matches_oracle(spec, cfg)
