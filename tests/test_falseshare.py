"""False-sharing detector (PL5xx) vs a line-granular schedule simulation.

The oracle below walks every access of the spec under the engine's
static chunk schedule and records, per (nest, array), whether two
DIFFERENT threads touch the same cache line at DIFFERENT element
addresses with at least one write — the literal definition of false
sharing, at line granularity.  The detector's verdicts are validated
against it exactly on several model families (the acceptance bar: ≥ 3),
including schedules that flip the verdict, plus adversarial intra-line
stride-1 specs and padded vs unpadded struct layouts.
"""

from __future__ import annotations

import pytest

from pluss.analysis import Severity, falseshare
from pluss.analysis.schedule import owner_of
from pluss.config import SamplerConfig
from pluss.models import REGISTRY
from pluss.spec import Loop, LoopNestSpec, Ref


def _walk_accesses(spec, cfg):
    """Yield (nest, array, addr, tid, is_write) for every access, under
    the static schedule (owner of parallel index k = (k // CS) % T)."""
    own = owner_of(cfg)

    def walk(item, ivs, k, ni):
        if isinstance(item, Ref):
            addr = item.addr_base + sum(c * ivs[d]
                                        for d, c in item.addr_terms)
            yield ni, item.array, addr, own(k), item.is_write, item.name
            return
        trip, start = item.trip, item.start
        if item.bound_coef is not None:
            a, b = item.bound_coef
            ref = k if item.bound_level == 0 else ivs[item.bound_level]
            trip = a + b * ref
        start = start + item.start_coef * k
        for i in range(trip):
            v = start + i * item.step
            for b_ in item.body:
                yield from walk(b_, ivs + [v], k, ni)

    for ni, nest in enumerate(spec.nests):
        for k in range(nest.trip):
            v0 = nest.start + k * nest.step
            for b_ in nest.body:
                yield from walk(b_, [v0], k, ni)


def line_share_oracle(spec, cfg):
    """{(nest, array)} with OBSERVED cross-thread same-line
    different-element contact (≥ one side a write), per array element
    widths (Ref.dtype_bytes else cfg.ds)."""
    per_line: dict = {}
    for ni, arr, addr, tid, w, name in _walk_accesses(spec, cfg):
        width = falseshare.array_width(spec, arr, cfg)
        E = max(1, cfg.cls // max(1, width))
        line = addr // E
        per_line.setdefault((ni, arr, line), set()).add((tid, addr, w))
    out = set()
    for (ni, arr, _line), touches in per_line.items():
        for t1, a1, w1 in touches:
            for t2, a2, w2 in touches:
                if t1 != t2 and a1 != a2 and (w1 or w2):
                    out.add((ni, arr))
    return out


def _detected(spec, cfg):
    diags = falseshare.check(spec, cfg)
    return {(d.nest, d.array) for d in diags
            if d.severity is Severity.WARNING}


# ---------------------------------------------------------------------------
# exact agreement with the line-granular simulation on model families
# ---------------------------------------------------------------------------

#: (family, n, thread_num, chunk_size) — covering verdicts that flip
#: with the schedule and with row alignment, on > 3 families
_SIM_CASES = [
    ("gemm", 16, 2, 2),      # line-aligned rows: refuted
    ("gemm", 12, 2, 1),      # straddling rows, fine chunks: confirmed
    ("gemm", 12, 2, 2),      # same rows, chunk pairs them: refuted
    ("jacobi2d", 12, 2, 1),
    ("jacobi2d", 12, 2, 2),
    ("stencil3d", 6, 2, 1),
    ("conv2d", 12, 2, 1),
    ("atax", 12, 2, 1),
    ("syrk", 12, 2, 1),
]


@pytest.mark.parametrize("name,n,T,CS", _SIM_CASES)
def test_verdicts_match_line_granular_simulation(name, n, T, CS):
    spec = REGISTRY[name](n)
    cfg = SamplerConfig(thread_num=T, chunk_size=CS)
    observed = line_share_oracle(spec, cfg)
    flagged = _detected(spec, cfg)
    # soundness: everything the simulation observes must be flagged
    assert observed <= flagged, (
        f"missed false sharing: {observed - flagged}")
    # exactness on these families/schedules: nothing spurious either
    assert flagged == observed, (
        f"spurious false-sharing findings: {flagged - observed}")


# ---------------------------------------------------------------------------
# adversarial specs: stride-1 counters, padded vs unpadded structs
# ---------------------------------------------------------------------------

def _counter_spec(stride: int, n: int = 16, name: str = "ctr"):
    """Per-parallel-iteration counter at ``stride`` elements apart —
    the canonical false-sharing victim when the stride is sub-line."""
    return LoopNestSpec(name, (("A", n * stride),), (Loop(trip=n, body=(
        Loop(trip=4, body=(
            Ref("A0", "A", addr_terms=((0, stride),), is_write=True),
        )),
    )),))


def test_unpadded_counter_flags_pl501():
    cfg = SamplerConfig(thread_num=2, chunk_size=2)   # E = 8
    diags = falseshare.check(_counter_spec(1), cfg)
    pl501 = [d for d in diags if d.code == "PL501"]
    assert pl501 and pl501[0].severity is Severity.WARNING
    assert "pad the per-iteration extent" in pl501[0].message
    assert line_share_oracle(_counter_spec(1), cfg)


def test_padded_counter_proves_pl503():
    cfg = SamplerConfig(thread_num=2, chunk_size=2)   # E = 8: stride 8
    diags = falseshare.check(_counter_spec(8), cfg)   # = one full line
    codes = {d.code for d in diags}
    assert "PL503" in codes and not {"PL501", "PL502"} & codes
    assert not line_share_oracle(_counter_spec(8), cfg)


def test_intra_line_stride_writes_across_threads():
    # stride 2 under E=8: four counters per line, neighbors on distinct
    # threads at chunk_size 1
    cfg = SamplerConfig(thread_num=2, chunk_size=1)
    spec = _counter_spec(2)
    assert _detected(spec, cfg) == {(0, "A")}
    assert line_share_oracle(spec, cfg) == {(0, "A")}


def test_dtype_bytes_override_flips_the_verdict():
    # stride-2 counters, 64 B lines: at the default 8 B elements E=8 and
    # neighbors falsely share; declared as 32 B struct elements E=2 and
    # the stride covers a full line — proven clean.  Same index math,
    # different machine model: exactly what Ref.dtype_bytes is for.
    def spec_of(dtype):
        return LoopNestSpec("dt", (("A", 32),), (Loop(trip=16, body=(
            Ref("A0", "A", addr_terms=((0, 2),), is_write=True,
                dtype_bytes=dtype),
        )),))

    cfg = SamplerConfig(thread_num=2, chunk_size=1)
    assert falseshare.array_width(spec_of(32), "A", cfg) == 32
    assert _detected(spec_of(None), cfg) == {(0, "A")}
    assert _detected(spec_of(32), cfg) == set()


def test_read_write_false_sharing_flags_pl502():
    # thread t writes A[2k], reads A[2k+1] — neighbors' slots: R-W on
    # shared lines, never W-W (distinct element parity)
    spec = LoopNestSpec("rw", (("A", 34),), (Loop(trip=16, body=(
        Ref("W0", "A", addr_terms=((0, 2),), is_write=True),
        Ref("R0", "A", addr_terms=((0, 2),), addr_base=1),
    )),))
    cfg = SamplerConfig(thread_num=2, chunk_size=1)
    codes = {d.code for d in falseshare.check(spec, cfg)}
    assert "PL502" in codes
    assert line_share_oracle(spec, cfg) == {(0, "A")}


def test_single_thread_schedule_refutes_everything():
    # T=1: no cross-thread pair exists, so even the stride-1 counter is
    # proven clean — the placement, not just the layout, decides
    cfg = SamplerConfig(thread_num=1, chunk_size=4)
    diags = falseshare.check(_counter_spec(1), cfg)
    codes = {d.code for d in diags}
    assert "PL503" in codes and "PL501" not in codes
