"""Byte-for-byte diff against the ACTUAL reference binaries.

Round 1 validated four implementations (engine, shard, native C++, oracle)
against each other — but all four came from one reading of the reference.
This module closes the loop: it compiles the reference's own C++ seq and
OpenMP samplers (/root/reference/c_lib/test/sampler/…omp{,-seq}.cpp, with the
runtime at …/runtime/pluss{,_utils}.cpp) using the GSL shim in
tests/gsl_shim/ (the one external symbol, gsl_ran_negative_binomial_pdf at
pluss_utils.h:1002, is provided via lgamma), runs their ``acc`` mode, and
diffs the output against ``pluss.cli acc`` **byte for byte** modulo the
timing banner — the reference's own golden-output criterion
(…omp-seq.cpp:334-362, run.sh:5-12, README.md:10-13).
"""

import hashlib
import subprocess
from pathlib import Path

import pytest

HERE = Path(__file__).resolve().parent
SHIM = HERE / "gsl_shim"
BUILD = SHIM / "build"
REF = Path("/root/reference/c_lib/test")

# the reference's build recipe: c_lib/test/Makefile:13-21.  THREADS/CHUNK/
# DS/CLS are the single source for both the binary's -D flags and the CLI
# arguments, so the two sides cannot drift apart silently.
THREADS, CHUNK, DS, CLS = 4, 4, 8, 64
CPPFLAGS = ["-std=c++17", "-O2", f"-DTHREAD_NUM={THREADS}",
            f"-DCHUNK_SIZE={CHUNK}", f"-DDS={DS}", f"-DCLS={CLS}",
            f"-I{SHIM}", f"-I{REF}/runtime"]
RUNTIME = [str(REF / "runtime/pluss.cpp"), str(REF / "runtime/pluss_utils.cpp")]

pytestmark = pytest.mark.skipif(not REF.exists(),
                                reason="reference tree not present")


def _build(name: str, sampler: str, extra: list[str],
           cppflags: list[str] | None = None) -> Path:
    """Compile one reference binary into tests/gsl_shim/build (cached).

    ``cppflags`` overrides the default config flags — the alternate-config
    parity test rebuilds at several -DTHREAD_NUM/-DCHUNK_SIZE pairs."""
    cmd = ["g++", *(CPPFLAGS if cppflags is None else cppflags), *extra,
           str(REF / "sampler" / sampler), *RUNTIME,
           "-lm", "-lpthread"]
    # cache key covers the full command line, the sources, the reference
    # runtime headers, and the shim headers
    tag = hashlib.sha1(" ".join(cmd).encode()).hexdigest()[:10]
    out = BUILD / f"{name}-{tag}"
    deps = ([Path(s) for s in cmd if s.endswith(".cpp")]
            + list((REF / "runtime").glob("*.h"))
            + list((SHIM / "gsl").iterdir()))
    if out.exists() and all(out.stat().st_mtime > d.stat().st_mtime
                            for d in deps):
        return out
    BUILD.mkdir(exist_ok=True)
    proc = subprocess.run([*cmd, "-o", str(out)], capture_output=True,
                          text=True)
    if proc.returncode != 0:
        pytest.fail(f"reference build failed:\n{proc.stderr}")
    return out


@pytest.fixture(scope="module")
def ref_seq_acc() -> str:
    binary = _build("ref-seq", "gemm-t4-pluss-pro-model-ri-omp-seq.cpp", [])
    return subprocess.run([str(binary), "acc"], check=True,
                          capture_output=True, text=True).stdout


def _body(block: str) -> str:
    """Strip the per-backend timing banner (line 1); keep everything else."""
    return "\n".join(block.splitlines()[1:])


@pytest.fixture(scope="module")
def our_seq_acc() -> str:
    from pluss import cli

    import io as _io
    import contextlib

    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        cli.main(["acc", "--cpu", "--n", "128", "--backends", "seq",
                  "--threads", str(THREADS), "--chunk", str(CHUNK)])
    return buf.getvalue()


def test_reference_seq_binary_matches_byte_for_byte(ref_seq_acc, our_seq_acc):
    """The one independent oracle in this environment: the reference's own
    compiled seq sampler.  Histogram dumps + max-iteration must agree to the
    byte (the banner differs by construction: 'SEQ C++:' vs 'TPU SEQ:')."""
    assert ref_seq_acc.splitlines()[0].startswith("SEQ C++: ")
    assert our_seq_acc.splitlines()[0].startswith("TPU SEQ: ")
    assert _body(ref_seq_acc) == _body(our_seq_acc)


def test_reference_openmp_binary_matches(ref_seq_acc):
    """The OpenMP baseline (the reference's other native block).  libgomp
    links in this image; its acc output must equal the seq binary's (and
    therefore ours)."""
    binary = _build("ref-omp", "gemm-t4-pluss-pro-model-ri-omp.cpp",
                    ["-fopenmp"])
    omp = subprocess.run([str(binary), "acc"], check=True,
                         capture_output=True, text=True).stdout
    assert omp.splitlines()[0].startswith("OPENMP C++: ")
    assert _body(omp) == _body(ref_seq_acc)


def test_reference_matches_our_native_twin(ref_seq_acc):
    """Our own C++ runtime (pluss/cpp) vs the reference binary — the two
    native paths must print identical bodies too."""
    import io as _io

    from pluss import native
    from pluss.io import acc_block
    from pluss.models import gemm

    if not native.available(autobuild=True):
        pytest.skip("native runtime unavailable")
    res = native.run(gemm(128))
    buf = _io.StringIO()
    acc_block("NATIVE", 0.0, res.noshare_list(), res.share_list(),
              res.rihist(), res.max_iteration_count, buf)
    # acc_block ends with a blank line like the reference's printf("\n")
    assert _body(ref_seq_acc).rstrip("\n") == _body(buf.getvalue()).rstrip("\n")


def test_reference_dispatcher_static_start_chunk_per_tid_rounding():
    """VERDICT r1 gap #3: the per-tid rounding edge of getStaticStartChunk
    (pluss_utils.h:474-490), diffed against the REFERENCE class itself.

    A probe binary (tests/dispatcher_probe.cpp) drives the reference's own
    ChunkDispatcher through setStartPoint(i) + getStaticStartChunk(i, t)
    for every thread; ChunkSchedule.static_start_chunk must reproduce every
    (lb, ub) pair — including the quirks: the resume point's intra-chunk
    offset applies to every thread, and only the far bound clamps, so late
    threads can return inverted (empty) ranges.
    """
    from pluss.sched import ChunkSchedule

    cmd = ["g++", *CPPFLAGS, str(HERE / "dispatcher_probe.cpp"), *RUNTIME,
           "-lm"]
    tag = hashlib.sha1(" ".join(cmd).encode()).hexdigest()[:10]
    out = BUILD / f"dispatcher-probe-{tag}"
    if not out.exists():
        BUILD.mkdir(exist_ok=True)
        proc = subprocess.run([*cmd, "-o", str(out)], capture_output=True,
                              text=True)
        if proc.returncode != 0:
            pytest.fail(f"probe build failed:\n{proc.stderr}")

    cases = [
        # (trip, start, step): incl. partial last chunk, nonzero start,
        # stride > 1, and a negative-step loop
        (16, 0, 1), (23, 0, 1), (16, 5, 1), (20, 0, 2), (30, 2, 3),
        (16, 15, -1),
    ]
    checked = 0
    for trip, start, step in cases:
        sched = ChunkSchedule(CHUNK, trip, start, step, THREADS)
        # resume points across rounds and intra-chunk offsets, incl. the
        # very last iteration value
        for k in sorted({0, 1, 3, 5, CHUNK * THREADS, CHUNK * THREADS + 2,
                         trip // 2, trip - 1}):
            if not 0 <= k < trip:
                continue
            i = start + k * step
            got = subprocess.run(
                [str(out), str(trip), str(start), str(step), str(i)],
                check=True, capture_output=True, text=True).stdout.split()
            ref = [(int(got[2 * t]), int(got[2 * t + 1]))
                   for t in range(THREADS)]
            ours = [sched.static_start_chunk(i, t) for t in range(THREADS)]
            assert ours == ref, (trip, start, step, i, ours, ref)
            checked += THREADS
    assert checked > 100


@pytest.mark.parametrize("threads,chunk", [(2, 8), (8, 2), (3, 5)])
def test_reference_alternate_configs_match(threads, chunk):
    """VERDICT r3 missing #2: config-generality against the one independent
    oracle.  Rebuild the reference's seq sampler at other compile-time
    configs (-DTHREAD_NUM/-DCHUNK_SIZE, c_lib/test/Makefile:13) and
    byte-diff acc output against ``cli acc --threads T --chunk C``.  The
    thread count T enters the CRI math itself (NBD p = 1/T, the racetrack
    exponent, the 4000*(T-1)/T cutoff), so each extra T is an independent
    check of the statistics pipeline, not just the schedule."""
    import contextlib
    import io as _io

    flags = [f for f in CPPFLAGS
             if not f.startswith(("-DTHREAD_NUM", "-DCHUNK_SIZE"))]
    flags += [f"-DTHREAD_NUM={threads}", f"-DCHUNK_SIZE={chunk}"]
    out = _build(f"ref-seq-t{threads}c{chunk}",
                 "gemm-t4-pluss-pro-model-ri-omp-seq.cpp", [],
                 cppflags=flags)
    ref = subprocess.run([str(out), "acc"], check=True, capture_output=True,
                         text=True).stdout

    from pluss import cli

    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        cli.main(["acc", "--cpu", "--n", "128", "--backends", "seq",
                  "--threads", str(threads), "--chunk", str(chunk)])
    assert _body(ref) == _body(buf.getvalue())
