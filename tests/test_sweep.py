"""Schedule sweeps: per-config predictions agree with direct runs."""

import dataclasses

import numpy as np

from pluss import cri, engine, mrc, sweep
from pluss.config import SamplerConfig
from pluss.models import gemm


def test_sweep_matches_direct_runs():
    pts = sweep.sweep(gemm(16), thread_nums=(1, 4), chunk_sizes=(2,),
                      base_cfg=SamplerConfig(cls=8))
    assert [(p.cfg.thread_num, p.cfg.chunk_size) for p in pts] == [(1, 2), (4, 2)]
    for p in pts:
        res = engine.run(gemm(16), p.cfg)
        ri = cri.distribute(res.noshare_list(), res.share_list(),
                            p.cfg.thread_num)
        want = mrc.aet_mrc(ri, p.cfg)
        assert np.array_equal(p.curve, want)
        assert p.total_refs == res.max_iteration_count
        assert p.miss_ratio_at(0) == 1.0
        assert p.miss_ratio_at(10**9) == p.curve[-1]


def test_sweep_table_shape():
    pts = sweep.sweep(gemm(16), thread_nums=(2,), chunk_sizes=(1, 4),
                      base_cfg=SamplerConfig(cls=8))
    txt = sweep.table(pts, [16, 256])
    lines = txt.splitlines()
    assert len(lines) == 3 and "mr@16" in lines[0] and "mr@256" in lines[0]


def test_cli_sweep_mode(capsys):
    from pluss import cli

    cli.main(["sweep", "--n", "16", "--cpu", "--sweep-threads", "1,2",
              "--sweep-chunks", "4", "--cache-lines", "64,1024"])
    got = capsys.readouterr().out
    assert "predicted miss ratios" in got and "mr@1024" in got
    lines = got.strip().splitlines()
    # title + header + 2 rows, then the PL303 carried-level block (the
    # static analyzer and the resilience stamps share this report surface)
    assert lines[1].split()[:2] == ["threads", "chunk"]
    assert len([l for l in lines if l.lstrip()[:1].isdigit()]) == 2
    assert "carried levels (PL303):" in got
