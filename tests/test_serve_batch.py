"""Admission queue + batcher semantics, the bounded-LRU disk plan cache,
and the engine's serving-facing demux surface."""

import os
import threading
import time

import numpy as np
import pytest

import tests.conftest  # noqa: F401  (CPU platform + x64)
from pluss import engine
from pluss.config import SamplerConfig
from pluss.models import REGISTRY
from pluss.resilience.errors import Overloaded
from pluss.serve.admission import AdmissionQueue
from pluss.serve.batcher import Batcher
from pluss.serve.protocol import parse_request


def req(i=None, model="gemm", n=16, **kw):
    obj = {"model": model, "n": n, "threads": 2, **kw}
    if i is not None:
        obj["id"] = str(i)
    return parse_request(obj)


# ---------------------------------------------------------------------------
# admission queue


def test_queue_fifo_and_len():
    q = AdmissionQueue(max_queue=8)
    for i in range(3):
        q.submit(req(i))
    assert len(q) == 3
    got, expired = q.pop(timeout=0)
    assert got.id == "0" and not expired
    assert [q.pop(0)[0].id for _ in range(2)] == ["1", "2"]


def test_queue_sheds_at_bound_with_typed_error():
    q = AdmissionQueue(max_queue=2)
    q.submit(req(0))
    q.submit(req(1))
    with pytest.raises(Overloaded) as ei:
        q.submit(req(2))
    assert ei.value.retryable, "clients may retry a shed after backoff"
    assert len(q) == 2, "the shed request must not occupy a slot"


def test_queue_closed_sheds_and_drains():
    q = AdmissionQueue(max_queue=8)
    q.submit(req(0))
    q.close()
    with pytest.raises(Overloaded):
        q.submit(req(1))
    got, _ = q.pop(timeout=0)
    assert got.id == "0", "queued work drains after close"
    got, _ = q.pop(timeout=0)
    assert got is None


def test_queue_pop_surfaces_expired():
    q = AdmissionQueue(max_queue=8)
    dead = req(0, deadline_ms=1)
    q.submit(dead)
    q.submit(req(1))
    time.sleep(0.01)
    got, expired = q.pop(timeout=0)
    assert got.id == "1"
    assert [r.id for r in expired] == ["0"]


def test_queue_take_matching_preserves_rest():
    q = AdmissionQueue(max_queue=16)
    a0, b0, a1, c0, a2 = (req(0), req(1, model="mvt"), req(2),
                          req(3, n=12), req(4))
    for r in (a0, b0, a1, c0, a2):
        q.submit(r)
    got, expired = q.take_matching(a0.batch_key(), limit=10)
    assert [r.id for r in got] == ["0", "2", "4"]
    assert not expired
    assert [q.pop(0)[0].id for _ in range(2)] == ["1", "3"]


def test_queue_take_matching_limit():
    q = AdmissionQueue(max_queue=16)
    for i in range(5):
        q.submit(req(i))
    got, _ = q.take_matching(req().batch_key(), limit=2)
    assert len(got) == 2 and len(q) == 3


def test_queue_take_matching_drains_expired_matches():
    """An expired same-key request must be REMOVED (and handed back for
    a DeadlineExceeded reply), not left queued — a left-behind entry
    would make the batcher's linger loop spin on a non-empty queue that
    never yields a member."""
    q = AdmissionQueue(max_queue=16)
    dead = req(0, deadline_ms=1)
    q.submit(dead)
    q.submit(req(1))
    time.sleep(0.01)
    got, expired = q.take_matching(dead.batch_key(), limit=10)
    assert [r.id for r in got] == ["1"]
    assert [r.id for r in expired] == ["0"]
    assert len(q) == 0


def test_queue_validation():
    with pytest.raises(ValueError):
        AdmissionQueue(max_queue=0)


# ---------------------------------------------------------------------------
# batcher


def test_batcher_coalesces_compatible():
    q = AdmissionQueue(max_queue=32)
    b = Batcher(q, max_batch=8, max_delay_ms=0)
    for i in range(5):
        q.submit(req(i))
    q.submit(req(9, model="mvt"))
    batch, expired = b.next_batch(timeout=0)
    assert [r.id for r in batch] == ["0", "1", "2", "3", "4"]
    assert not expired
    batch, _ = b.next_batch(timeout=0)
    assert [r.id for r in batch] == ["9"]


def test_batcher_max_batch_cap():
    q = AdmissionQueue(max_queue=32)
    b = Batcher(q, max_batch=3, max_delay_ms=0)
    for i in range(5):
        q.submit(req(i))
    assert len(b.next_batch(timeout=0)[0]) == 3
    assert len(b.next_batch(timeout=0)[0]) == 2


def test_batcher_unbatched_mode():
    q = AdmissionQueue(max_queue=32)
    b = Batcher(q, max_batch=1, max_delay_ms=50)
    for i in range(3):
        q.submit(req(i))
    t0 = time.monotonic()
    assert len(b.next_batch(timeout=0)[0]) == 1
    assert time.monotonic() - t0 < 0.04, "max_batch=1 must never linger"


def test_batcher_adaptive_window_catches_straggler():
    q = AdmissionQueue(max_queue=32)
    b = Batcher(q, max_batch=8, max_delay_ms=200)
    q.submit(req(0))

    def straggle():
        time.sleep(0.03)
        q.submit(req(1))

    t = threading.Thread(target=straggle)
    t.start()
    batch, _ = b.next_batch(timeout=0)
    t.join()
    assert [r.id for r in batch] == ["0", "1"], \
        "the adaptive window must pick up a straggler within max_delay"


def test_batcher_ships_early_when_other_work_waits():
    q = AdmissionQueue(max_queue=32)
    b = Batcher(q, max_batch=8, max_delay_ms=10_000)
    q.submit(req(0))
    q.submit(req(1, model="mvt"))
    t0 = time.monotonic()
    batch, _ = b.next_batch(timeout=0)
    assert [r.id for r in batch] == ["0"]
    assert time.monotonic() - t0 < 1.0, \
        "unrelated queued work must abort the linger immediately"


def test_batcher_singleton_ships_after_delay():
    q = AdmissionQueue(max_queue=32)
    b = Batcher(q, max_batch=8, max_delay_ms=30)
    q.submit(req(0))
    t0 = time.monotonic()
    batch, _ = b.next_batch(timeout=0)
    dt = time.monotonic() - t0
    assert [r.id for r in batch] == ["0"]
    assert dt < 1.0


def test_batcher_validation():
    q = AdmissionQueue(max_queue=2)
    with pytest.raises(ValueError):
        Batcher(q, max_batch=0)
    with pytest.raises(ValueError):
        Batcher(q, max_delay_ms=-1)


# ---------------------------------------------------------------------------
# bounded-LRU disk plan cache


@pytest.fixture
def plan_cache_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("PLUSS_NO_PLAN_CACHE", raising=False)
    monkeypatch.setenv("PLUSS_PLAN_CACHE_DIR", str(tmp_path))
    return tmp_path


def _entries(root):
    return sorted(p.name for p in root.iterdir() if p.name.endswith(".pkl"))


def test_plan_cache_lru_eviction(plan_cache_dir, monkeypatch):
    monkeypatch.setenv("PLUSS_PLAN_CACHE_MAX", "2")
    for i, key in enumerate(["k1", "k2", "k3"]):
        engine._plan_cache_put(key, {"tpl": None, "overlays": ()})
        os.utime(plan_cache_dir / f"{key}.pkl", (i, i))   # force ordering
    engine._plan_cache_evict()
    assert _entries(plan_cache_dir) == ["k2.pkl", "k3.pkl"], \
        "the oldest entry must be evicted past the cap"


def test_plan_cache_hit_refreshes_recency(plan_cache_dir, monkeypatch):
    monkeypatch.setenv("PLUSS_PLAN_CACHE_MAX", "2")
    engine._plan_cache_put("hot", {"tpl": None, "overlays": ()})
    os.utime(plan_cache_dir / "hot.pkl", (1, 1))    # oldest by mtime...
    engine._plan_cache_put("warm", {"tpl": None, "overlays": ()})
    # pin warm well in the past too (tmpfs mtime granularity is coarse —
    # a same-tick tie would make the eviction order arbitrary); the HIT
    # below must refresh hot far past both
    os.utime(plan_cache_dir / "warm.pkl", (2, 2))
    assert engine._plan_cache_get("hot") is not None   # ...but HIT now
    assert (plan_cache_dir / "hot.pkl").stat().st_mtime > 2, \
        "a cache hit must touch the entry's mtime"
    engine._plan_cache_put("new", {"tpl": None, "overlays": ()})
    assert "hot.pkl" in _entries(plan_cache_dir), \
        "a hit must refresh LRU recency: the untouched entry evicts first"
    assert "warm.pkl" not in _entries(plan_cache_dir)


def test_plan_cache_evict_counter(plan_cache_dir, monkeypatch, tmp_path):
    from pluss import obs

    monkeypatch.setenv("PLUSS_PLAN_CACHE_MAX", "1")
    sink = tmp_path / "tel.jsonl"
    obs.configure(str(sink))
    try:
        for key in ("a", "b", "c"):
            engine._plan_cache_put(key, {"tpl": None})
        assert obs.counters().get("engine.plan_cache.evict") == 2
    finally:
        obs.shutdown()


def test_plan_cache_unbounded_when_disabled(plan_cache_dir, monkeypatch):
    monkeypatch.setenv("PLUSS_PLAN_CACHE_MAX", "0")
    for i in range(5):
        engine._plan_cache_put(f"k{i}", {"tpl": None})
    assert len(_entries(plan_cache_dir)) == 5


def test_plan_cache_real_plan_round_trip(plan_cache_dir, monkeypatch):
    """A real planned spec still round-trips through the capped cache
    (the eviction path must not corrupt the artifact discipline)."""
    monkeypatch.setenv("PLUSS_PLAN_CACHE_MAX", "4")
    engine.compiled.cache_clear()
    spec = REGISTRY["gemm"](16)
    cfg = SamplerConfig(thread_num=2, chunk_size=2)
    r1 = engine.run(spec, cfg)
    engine.compiled.cache_clear()   # force a re-plan → disk cache hit
    r2 = engine.run(spec, cfg)
    assert r1.noshare_dense.tolist() == r2.noshare_dense.tolist()
    assert r1.share_raw == r2.share_raw
    assert _entries(plan_cache_dir), "the plan artifact must be cached"
    engine.compiled.cache_clear()


# ---------------------------------------------------------------------------
# engine serving surface: dispatch keys + tenant demux


def test_dispatch_key_identity():
    spec = REGISTRY["gemm"](16)
    cfg = SamplerConfig(thread_num=2)
    k = engine.dispatch_key(spec, cfg, 64, None)
    assert k == engine.dispatch_key(REGISTRY["gemm"](16), cfg, 64, None)
    assert k != engine.dispatch_key(spec, cfg, 64, 4096)
    assert k != engine.dispatch_key(spec, SamplerConfig(thread_num=4),
                                    64, None)
    assert k != engine.dispatch_key(REGISTRY["gemm"](12), cfg, 64, None)
    # cache_kb is post-dispatch only: it must not split dispatch groups
    assert k == engine.dispatch_key(
        spec, SamplerConfig(thread_num=2, cache_kb=512), 64, None)
    hash(k)   # usable as a grouping dict key


def test_tenant_view_isolation():
    spec = REGISTRY["gemm"](13)
    cfg = SamplerConfig(thread_num=2, chunk_size=2)
    res = engine.run(spec, cfg)
    a, b = res.tenant_view(), res.tenant_view()
    orig_hist = res.noshare_dense.copy()
    orig_share = [dict(d) for d in res.share_raw]
    a.noshare_dense[:] = -7
    a.share_raw[0][999999] = 42.0
    assert b.noshare_dense.tolist() == orig_hist.tolist()
    assert b.share_raw == orig_share
    assert res.noshare_dense.tolist() == orig_hist.tolist()
    assert res.share_raw == orig_share


def test_tenant_view_preserves_stamps():
    spec = REGISTRY["gemm"](13)
    cfg = SamplerConfig(thread_num=2, chunk_size=2)
    res = engine.run(spec, cfg)
    res.degradations = ("shrink_window",)
    v = res.tenant_view()
    assert v.degradations == ("shrink_window",)
    assert v.max_iteration_count == res.max_iteration_count
    assert v.share_ratio == res.share_ratio


def test_batched_equals_solo_bit_identical():
    """The whole coalescing contract in one assertion: one dispatch's
    demuxed views equal K independent runs, bit for bit."""
    from pluss import cri

    spec = REGISTRY["mvt"](12)
    cfg = SamplerConfig(thread_num=2, chunk_size=2)
    shared = engine.run(spec, cfg)
    views = [shared.tenant_view() for _ in range(3)]
    solo = engine.run(spec, cfg)
    for v in views:
        assert v.noshare_dense.tolist() == solo.noshare_dense.tolist()
        assert v.share_raw == solo.share_raw
        ri_v = cri.distribute(v.noshare_list(), v.share_list(),
                              cfg.thread_num)
        ri_s = cri.distribute(solo.noshare_list(), solo.share_list(),
                              cfg.thread_num)
        assert ri_v == ri_s
