/* Minimal GSL shim for building the reference sampler as a test oracle.
 *
 * The reference runtime (/root/reference/c_lib/test/runtime/pluss_utils.h:20-22)
 * includes GSL for exactly one live call: gsl_ran_negative_binomial_pdf at
 * pluss_utils.h:1002 (the NBD dilation).  GSL is not installed in this image,
 * so we provide the same function here, computed the way GSL itself does
 * (gsl_ran_negative_binomial_pdf in GSL's randist/nbinomial.c evaluates
 * exp(lngamma terms) with the P(k) = Gamma(n+k)/(Gamma(k+1)Gamma(n))
 * p^n (1-p)^k parameterization).  At the 6-significant-digit precision the
 * reference prints (default std::cout), libm lgamma and GSL lngamma agree.
 *
 * This header exists so the ACTUAL reference binary can be compiled and run
 * as an independent oracle; it contains no reference code.
 */
#ifndef PLUSS_TEST_GSL_RANDIST_SHIM_H
#define PLUSS_TEST_GSL_RANDIST_SHIM_H

#include <math.h>

static inline double
gsl_ran_negative_binomial_pdf(const unsigned int k, const double p,
                              const double n)
{
    if (p <= 0.0 || p > 1.0 || n <= 0.0)
        return 0.0;
    return exp(lgamma(n + (double)k) - lgamma((double)k + 1.0) - lgamma(n)
               + n * log(p) + (double)k * log1p(-p));
}

#endif /* PLUSS_TEST_GSL_RANDIST_SHIM_H */
