/* Shim: the reference includes <gsl/gsl_rng.h> (pluss_utils.h:20) but never
 * uses any RNG symbol in live code.  Nothing to declare. */
#ifndef PLUSS_TEST_GSL_RNG_SHIM_H
#define PLUSS_TEST_GSL_RNG_SHIM_H
#endif
