/* Shim: <gsl/gsl_cdf.h> (pluss_utils.h:22) is only needed by the reference's
 * #if 0-disabled geometric-CDF racetrack variant (pluss_utils.h:1132-1203);
 * no live symbol is required.  Declared for completeness in case a build
 * enables that region. */
#ifndef PLUSS_TEST_GSL_CDF_SHIM_H
#define PLUSS_TEST_GSL_CDF_SHIM_H

#include <math.h>

static inline double gsl_cdf_geometric_P(const unsigned int k, const double p)
{
    if (k < 1)
        return 0.0;
    return -expm1((double)k * log1p(-p));
}

#endif /* PLUSS_TEST_GSL_CDF_SHIM_H */
