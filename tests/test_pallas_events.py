"""Pallas fused event histogram (pluss.ops.pallas_events) vs the XLA path.

On the CPU mesh the kernel runs in interpret mode — same code the TPU
compiles.  The kernel is strictly flag-gated; these tests call it directly
and through the engine flag."""

import numpy as np
import pytest

import jax.numpy as jnp

from pluss import engine
from pluss.config import SamplerConfig
from pluss.models import gemm, syrk_triangular
from pluss.ops import pallas_events
from pluss.ops.reuse import carried_events, event_histogram, sort_stream


@pytest.mark.parametrize("seed,n,n_lines", [(0, 4096, 64), (1, 50000, 300)])
def test_fused_matches_xla(seed, n, n_lines):
    rng = np.random.default_rng(seed)
    line = rng.integers(0, n_lines, n).astype(np.int32)
    pos = np.sort(rng.choice(10 * n, n, replace=False)).astype(np.int32)
    # shuffle into line-major order like a real sorted window, with ghosts
    span = np.where(rng.random(n) < 0.3, 2, 0).astype(np.int32)
    valid = rng.random(n) < 0.95
    key_s, pos_s, span_s, valid_s = sort_stream(
        jnp.asarray(line), jnp.asarray(pos), jnp.asarray(span),
        jnp.asarray(valid))
    win_start = np.int32(5 * n // 2)
    ev = carried_events(key_s, pos_s, span_s, valid_s, win_start)
    want = np.asarray(event_histogram(ev))
    got = np.asarray(pallas_events.event_histogram_fused(
        key_s, pos_s, span_s, valid_s, win_start, jnp.int32))
    np.testing.assert_array_equal(got, want)


def test_engine_flag_matches_default(monkeypatch):
    spec = syrk_triangular(13)
    cfg = SamplerConfig(cls=8)
    a = engine.run(spec, cfg)
    monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "1")
    engine.compiled.cache_clear()
    b = engine.run(spec, cfg)
    monkeypatch.delenv("PLUSS_PALLAS_EVENTS")
    engine.compiled.cache_clear()
    assert a.max_iteration_count == b.max_iteration_count
    np.testing.assert_array_equal(a.noshare_dense, b.noshare_dense)
    assert a.share_list() == b.share_list()


def test_engine_flag_matches_default_gemm(monkeypatch):
    # partial chunks -> sort windows on the template path too
    spec = gemm(13)
    a = engine.run(spec)
    monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "1")
    engine.compiled.cache_clear()
    b = engine.run(spec)
    monkeypatch.delenv("PLUSS_PALLAS_EVENTS")
    engine.compiled.cache_clear()
    np.testing.assert_array_equal(a.noshare_dense, b.noshare_dense)
    assert a.share_list() == b.share_list()
