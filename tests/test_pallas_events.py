"""Fused Pallas kernels (pallas_events + pallas_decode) vs the XLA path.

On the CPU mesh the kernels run in interpret mode — same code the TPU
compiles.  Since r19 the fused event histogram is the promoted post-sort
default (accelerators; probe-guarded) and the d24v decode has a Pallas
twin, so the equivalence matrix here is the promotion gate: fused vs XLA
bit-identity across wire formats, ragged tails, cross-batch carries, and
fault-interrupted resume splits.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pluss import engine, trace
from pluss.config import SamplerConfig
from pluss.models import gemm, syrk_triangular
from pluss.ops import pallas_decode, pallas_events, wirecodec
from pluss.ops.reuse import carried_events, event_histogram, sort_stream


@pytest.fixture
def fused_on(monkeypatch):
    """Force both fused kernels on (interpret mode on CPU), restoring the
    probe/memo caches on the way out so later tests see a clean slate."""
    monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "1")
    monkeypatch.setenv("PLUSS_PALLAS_DECODE", "1")
    pallas_events.reset_probe()
    pallas_decode.reset_probe()
    yield
    pallas_events.reset_probe()
    pallas_decode.reset_probe()


@pytest.mark.parametrize("seed,n,n_lines", [(0, 4096, 64), (1, 50000, 300)])
def test_fused_matches_xla(seed, n, n_lines):
    rng = np.random.default_rng(seed)
    line = rng.integers(0, n_lines, n).astype(np.int32)
    pos = np.sort(rng.choice(10 * n, n, replace=False)).astype(np.int32)
    # shuffle into line-major order like a real sorted window, with ghosts
    span = np.where(rng.random(n) < 0.3, 2, 0).astype(np.int32)
    valid = rng.random(n) < 0.95
    key_s, pos_s, span_s, valid_s = sort_stream(
        jnp.asarray(line), jnp.asarray(pos), jnp.asarray(span),
        jnp.asarray(valid))
    win_start = np.int32(5 * n // 2)
    ev = carried_events(key_s, pos_s, span_s, valid_s, win_start)
    want = np.asarray(event_histogram(ev))
    got = np.asarray(pallas_events.event_histogram_fused(
        key_s, pos_s, span_s, valid_s, win_start, jnp.int32))
    np.testing.assert_array_equal(got, want)


def test_engine_flag_matches_default(monkeypatch):
    spec = syrk_triangular(13)
    cfg = SamplerConfig(cls=8)
    a = engine.run(spec, cfg)
    monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "1")
    engine.compiled.cache_clear()
    b = engine.run(spec, cfg)
    monkeypatch.delenv("PLUSS_PALLAS_EVENTS")
    engine.compiled.cache_clear()
    assert a.max_iteration_count == b.max_iteration_count
    np.testing.assert_array_equal(a.noshare_dense, b.noshare_dense)
    assert a.share_list() == b.share_list()


def test_engine_flag_matches_default_gemm(monkeypatch):
    # partial chunks -> sort windows on the template path too
    spec = gemm(13)
    a = engine.run(spec)
    monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "1")
    engine.compiled.cache_clear()
    b = engine.run(spec)
    monkeypatch.delenv("PLUSS_PALLAS_EVENTS")
    engine.compiled.cache_clear()
    np.testing.assert_array_equal(a.noshare_dense, b.noshare_dense)
    assert a.share_list() == b.share_list()


# ---------------------------------------------------------------------------
# envknob gating (r19 satellite: PLUSS_PALLAS_EVENTS=0 must mean OFF)


def test_env_bool_tristate(capsys):
    from pluss.utils.envknob import env_bool

    for raw, want in (("1", True), ("true", True), ("YES", True),
                      ("on", True), ("0", False), ("false", False),
                      ("No", False), ("off", False), ("", None)):
        os.environ["PLUSS_TEST_BOOL"] = raw
        try:
            assert env_bool("PLUSS_TEST_BOOL", None) is want, raw
        finally:
            del os.environ["PLUSS_TEST_BOOL"]
    assert env_bool("PLUSS_TEST_BOOL_UNSET", None) is None
    assert env_bool("PLUSS_TEST_BOOL_UNSET", True) is True
    os.environ["PLUSS_TEST_BOOL_BAD"] = "bananas"
    try:
        assert env_bool("PLUSS_TEST_BOOL_BAD", False) is False
    finally:
        del os.environ["PLUSS_TEST_BOOL_BAD"]
    assert "malformed" in capsys.readouterr().err


def test_env_zero_really_disables(monkeypatch):
    """The pre-r19 bug: enabled() tested presence, so =0 ENABLED the
    kernel.  Now =0 must resolve to off on any backend."""
    monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "0")
    monkeypatch.setenv("PLUSS_PALLAS_DECODE", "0")
    assert pallas_events.enabled() is False
    assert pallas_decode.enabled() is False
    monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "1")
    monkeypatch.setenv("PLUSS_PALLAS_DECODE", "1")
    assert pallas_events.enabled() is True
    assert pallas_decode.enabled() is True


def test_cpu_default_is_off(monkeypatch):
    """Unset env + no tuned geometry -> the CPU backend stays on the XLA
    path (the interpreter kernel exists for tests, not production)."""
    monkeypatch.delenv("PLUSS_PALLAS_EVENTS", raising=False)
    monkeypatch.delenv("PLUSS_PALLAS_DECODE", raising=False)
    monkeypatch.setenv("PLUSS_AUTOTUNE", "0")   # no sidecar consult
    assert jax.default_backend() == "cpu"
    assert pallas_events.enabled() is False
    assert pallas_decode.enabled() is False


def test_probe_failure_degrades_loudly(monkeypatch, capsys):
    """A lowering/compile failure must count pallas.fallback, print one
    stderr line, and resolve enabled() False even under env=1 — the
    promotion can never crash a replay."""
    from pluss import obs

    monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "1")
    pallas_events.reset_probe()

    def boom(*a, **k):
        raise RuntimeError("synthetic lowering failure")

    monkeypatch.setattr(pallas_events, "_probe_impl", boom)
    obs.shutdown()
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        obs.configure(os.path.join(td, "ev.jsonl"))
        try:
            assert pallas_events.enabled() is False
            c = obs.counters()
        finally:
            obs.shutdown()
    assert c.get("pallas.probe", 0) >= 1
    assert c.get("pallas.fallback", 0) >= 1
    err = capsys.readouterr().err
    assert "using the XLA path" in err
    pallas_events.reset_probe()
    # a clean probe afterwards recovers (the verdict was memoized, not
    # sticky beyond reset)
    monkeypatch.undo()
    pallas_events.reset_probe()
    assert pallas_events.probe_ok() is True


def test_memo_key_includes_device_kind():
    """r19 satellite: the kernel memo must key on the device kind so a
    TPU-generation switch under one backend string rebuilds."""
    pallas_events.reset_probe()
    a = pallas_events._event_hist_fn(pallas_events.BLOCK, "int32",
                                     "cpu", "kind-A")
    b = pallas_events._event_hist_fn(pallas_events.BLOCK, "int32",
                                     "cpu", "kind-B")
    assert a is not b
    assert pallas_events._event_hist_fn(
        pallas_events.BLOCK, "int32", "cpu", "kind-A") is a
    pallas_events.reset_probe()


def test_padded_n_quantized():
    """r19 satellite: ragged windows land on a bounded set of padded
    lengths (the wirecodec pad_len trick) instead of one retrace per
    distinct length."""
    B = pallas_events.BLOCK
    assert pallas_events._padded_n(1) == B
    assert pallas_events._padded_n(B) == B
    assert pallas_events._padded_n(B + 1) == 2 * B
    lens = {pallas_events._padded_n(n)
            for n in range(1, 2_000_000, 4093)}
    for n in range(1, 3_000_000, 9973):
        p = pallas_events._padded_n(n)
        assert p >= n and p % B == 0
    # a 2e6 range of raw lengths collapses to a bounded shape set:
    # exact block counts through 8 blocks, then eighth-octave rounding —
    # at most 8 shapes per octave, ~6 octaves at 2e6 refs
    assert len(lens) <= 56, sorted(lens)


# ---------------------------------------------------------------------------
# Pallas d24v decode vs the XLA wirecodec decode


def _id_patterns():
    rng = np.random.default_rng(11)
    B = wirecodec.BLOCK
    return {
        "sequential": np.arange(4 * B, dtype=np.int32) % (1 << 20),
        "random24": rng.integers(0, 1 << 24, 3 * B).astype(np.int32),
        "mix": np.concatenate([
            np.arange(B, dtype=np.int32),                 # delta, narrow
            rng.integers(0, 1 << 24, B).astype(np.int32),  # raw
            np.full(B, 7, np.int32),                       # delta, k=1
            rng.integers(0, 1 << 10, B // 2).astype(np.int32)]),  # ragged
        "zeros": np.zeros(2 * B, np.int32),
        "tiny_ragged": np.arange(37, dtype=np.int32) * 5,
        "strided": (np.arange(2 * B, dtype=np.int32) * 4097) % (1 << 24),
    }


@pytest.mark.parametrize("name", sorted(_id_patterns()))
def test_decode_d24v_bit_identical(name):
    ids = _id_patterns()[name]
    payload, wm = wirecodec.encode_d24v(ids)
    ref = np.asarray(wirecodec.decode_d24v(jnp.asarray(payload),
                                           jnp.asarray(wm)))
    # the jit executes the interpret-mode pallas_call (no eager eval rule)
    got = np.asarray(jax.jit(pallas_decode.decode_d24v)(
        jnp.asarray(payload), jnp.asarray(wm)))
    np.testing.assert_array_equal(got, ref, err_msg=name)
    np.testing.assert_array_equal(got[:len(ids)], ids, err_msg=name)


def test_decode_probe_ok_on_cpu():
    pallas_decode.reset_probe()
    assert pallas_decode.probe_ok() is True
    pallas_decode.reset_probe()


# ---------------------------------------------------------------------------
# full-pipeline equivalence matrix: fused vs XLA through replay_file


def _write_trace(path, n_refs, seed=5):
    rng = np.random.default_rng(seed)
    lines = np.concatenate([
        rng.integers(0, 1 << 10, n_refs // 2, dtype=np.int64),
        rng.integers(0, 1 << 15, n_refs - n_refs // 2, dtype=np.int64)])
    rng.shuffle(lines)
    (lines.astype(np.uint64) << np.uint64(6)).astype("<u8").tofile(path)


#: n_refs = 3 batches of (2 windows x 4096) + a ragged 1500-ref tail:
#: cross-batch carries AND a non-BLOCK-multiple final window
_N_REFS = 3 * 2 * 4096 + 1500
_GEO = dict(window=4096, batch_windows=2, segmented=True)


@pytest.mark.parametrize("wire", ["pack", "d24v"])
def test_replay_fused_matches_xla(tmp_path, monkeypatch, fused_on, wire):
    path = str(tmp_path / "t.bin")
    _write_trace(path, _N_REFS)
    fused = trace.replay_file(path, wire=wire, **_GEO)
    monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "0")
    monkeypatch.setenv("PLUSS_PALLAS_DECODE", "0")
    ref = trace.replay_file(path, wire=wire, **_GEO)
    assert fused.total_count == ref.total_count == _N_REFS
    np.testing.assert_array_equal(fused.hist, ref.hist)


@pytest.mark.parametrize("wire", ["pack", "d24v"])
def test_replay_fused_resume_split(tmp_path, monkeypatch, fused_on, wire):
    """Fault-interrupted checkpoint --resume under the fused kernels must
    reproduce the uninterrupted XLA histogram bit-exactly — the carry
    state crosses the checkpoint boundary through the same last_pos
    contract either way."""
    from pluss.resilience import faults
    from pluss.resilience.errors import DataLoss

    path = str(tmp_path / "t.bin")
    _write_trace(path, _N_REFS)
    monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "0")
    monkeypatch.setenv("PLUSS_PALLAS_DECODE", "0")
    ref = trace.replay_file(path, wire=wire, **_GEO)
    monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "1")
    monkeypatch.setenv("PLUSS_PALLAS_DECODE", "1")
    ckpt = str(tmp_path / "t.ckpt.npz")
    faults.install(faults.FaultPlan.parse("trace_loss@2"))
    try:
        with pytest.raises(DataLoss):
            trace.replay_file(path, wire=wire, checkpoint_path=ckpt,
                              checkpoint_every=1, **_GEO)
    finally:
        faults.install(None)
    assert os.path.exists(ckpt)
    resumed = trace.replay_file(path, wire=wire, checkpoint_path=ckpt,
                                resume=True, **_GEO)
    assert resumed.total_count == ref.total_count == _N_REFS
    np.testing.assert_array_equal(resumed.hist, ref.hist)


def test_shard_dispatch_fused_matches_xla(tmp_path, monkeypatch, fused_on):
    """Both sharded dispatch modes consume the fused post-sort consumer
    through ops.reuse.event_histogram — bit-identical to the XLA path."""
    path = str(tmp_path / "t.bin")
    _write_trace(path, _N_REFS)
    out = {}
    for mode in ("steal", "static"):
        monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "1")
        monkeypatch.setenv("PLUSS_PALLAS_DECODE", "1")
        fused = trace.shard_replay_file(path, window=4096,
                                        batch_windows=2, dispatch=mode)
        monkeypatch.setenv("PLUSS_PALLAS_EVENTS", "0")
        monkeypatch.setenv("PLUSS_PALLAS_DECODE", "0")
        ref = trace.shard_replay_file(path, window=4096,
                                      batch_windows=2, dispatch=mode)
        np.testing.assert_array_equal(fused.hist, ref.hist,
                                      err_msg=f"dispatch={mode}")
        out[mode] = np.asarray(ref.hist)
    np.testing.assert_array_equal(out["steal"], out["static"])


def test_fused_vmap_batch_matches_xla():
    """The engine's thread-vmap wraps the fused histogram in a batch
    dimension; the interpret-mode kernel must batch bit-identically."""
    rng = np.random.default_rng(3)
    n = 4096
    ev = {
        "reuse": jnp.asarray(rng.integers(1, 1 << 16, (4, n)), jnp.int32),
        "is_evt": jnp.asarray(rng.random((4, n)) < 0.6),
        "share": jnp.asarray(rng.random((4, n)) < 0.1),
        "cold": jnp.asarray(rng.random((4, n)) < 0.2),
    }
    want = np.asarray(jax.vmap(event_histogram)(ev))
    got = np.asarray(jax.vmap(pallas_events.fused_event_histogram)(ev))
    np.testing.assert_array_equal(got, want)
