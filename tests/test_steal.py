"""Work-stealing sharded dispatch ≡ static shard_map ≡ vmap engine.

The PR-9 contract: the chunk partition and the canonical-order boundary
merge are the ONLY things that reach a sharded result — which device ran a
chunk, in what order, under which steal schedule, and through which window
kernel (segmented batch_events vs the legacy ghost merge) are all
bit-identity-invariant.  Pinned here across steal seeds, device counts,
dispatch modes, and kernels, on affine, ultra+var, and quad (clock-table)
nests — plus the dispatcher's own scheduling semantics, the iterative
share-cap retry, the device-group sweep, and the README/stat-block sync.
"""

import io
import time

import numpy as np
import pytest

from pluss.config import SamplerConfig
from pluss.engine import run
from pluss.models import REGISTRY, gemm
from pluss.parallel import default_mesh, shard_run
from pluss.parallel.steal import QueueDispatcher, StealDispatcher


def assert_same(a, b, what=""):
    assert a.max_iteration_count == b.max_iteration_count, what
    assert a.noshare_dense.tolist() == b.noshare_dense.tolist(), what
    assert a.share_raw == b.share_raw, what


# ---------------------------------------------------------------------------
# dispatcher unit semantics (pure host, no jax)


def test_steal_dispatcher_runs_every_chunk_once():
    for n_chunks, n_workers in ((13, 4), (3, 8), (1, 2), (0, 3), (8, 1)):
        ran = []
        disp = StealDispatcher(n_chunks, n_workers,
                               lambda wi, ci: ran.append(ci), seed=0)
        stats = disp.run()
        assert sorted(ran) == list(range(n_chunks))
        assert stats["chunks"] == n_chunks
        assert sum(stats["chunks_per_worker"]) == n_chunks


def test_steal_dispatcher_steals_from_stragglers():
    # worker 0's chunks are slow: idle workers must steal its tail
    def run_chunk(wi, ci):
        time.sleep(0.05 if ci < 8 else 0.001)

    disp = StealDispatcher(16, 2, run_chunk, seed=0)
    stats = disp.run()
    assert stats["steals"] >= 1, "no steal despite a straggler-bound deque"
    assert sorted(stats["ran_by"]) == list(range(16))


def test_steal_dispatcher_seed_permutes_schedule_only():
    # the rotation deal moves chunks between workers deterministically
    # with the seed (victim tie-breaks add run-time variation on top);
    # every chunk still runs exactly once whatever the deal
    deals = set()
    for seed in range(4):
        done = []
        disp = StealDispatcher(12, 3, lambda wi, ci: done.append(ci),
                               seed=seed)
        deals.add(tuple(tuple(d) for d in disp._deques))
        disp.run()
        assert sorted(done) == list(range(12))
    assert len(deals) >= 2, "seeds never permuted the chunk->device deal"


def test_steal_dispatcher_propagates_worker_error():
    def boom(wi, ci):
        if ci == 5:
            raise RuntimeError("chunk 5 died")

    with pytest.raises(RuntimeError, match="chunk 5"):
        StealDispatcher(8, 2, boom, seed=0).run()


def test_queue_dispatcher_pulls_and_counts_steals():
    done = []
    disp = QueueDispatcher(2, lambda wi, ci, payload: done.append(ci),
                           depth=2)
    stats = disp.run(((i, None) for i in range(9)), 9)
    assert sorted(done) == list(range(9))
    assert stats["chunks"] == 9


def test_queue_dispatcher_error_does_not_deadlock():
    def boom(wi, ci, payload):
        if ci == 1:
            raise ValueError("chunk 1 died")
        time.sleep(0.01)

    with pytest.raises(ValueError, match="chunk 1"):
        QueueDispatcher(2, boom, depth=1).run(
            ((i, None) for i in range(50)), 50)


def test_queue_dispatcher_producer_error_propagates():
    def produce():
        yield 0, None
        raise OSError("feed died")

    with pytest.raises(OSError, match="feed died"):
        QueueDispatcher(2, lambda wi, ci, p: None, depth=2).run(
            produce(), 2)


# ---------------------------------------------------------------------------
# steal dispatch ≡ engine, across seeds / device counts / kernels.
# Families: affine template (gemm), ultra+var split (syrk), and a QUAD
# clock-table nest (cholesky) — the straggler-bound shape stealing is for.

STEAL_FAMILIES = [
    ("gemm16", lambda: gemm(16), SamplerConfig(cls=8)),
    # tier-1 keeps the affine-template representative; the ultra+var and
    # QUAD families re-run the same seed/device matrix and live in -m slow
    pytest.param("syrk32", lambda: REGISTRY["syrk"](32), SamplerConfig(),
                 marks=pytest.mark.slow),
    pytest.param("cholesky16", lambda: REGISTRY["cholesky"](16),
                 SamplerConfig(cls=8), marks=pytest.mark.slow),
]


@pytest.mark.parametrize("name,build,cfg", STEAL_FAMILIES,
                         ids=["gemm16", "syrk32", "cholesky16"])
def test_steal_permutations_bit_identical_to_engine(name, build, cfg):
    spec = build()
    want = run(spec, cfg)
    for n_dev, seeds in ((2, (0,)), (4, (0, 3)), (8, (0,))):
        for seed in seeds:
            got = shard_run(spec, cfg, mesh=default_mesh(n_dev),
                            dispatch="steal", steal_seed=seed)
            assert got.dispatch_stats["dispatch"] == "steal"
            assert_same(want, got, f"{name} D={n_dev} seed={seed}")


@pytest.mark.slow   # shard_static_segmented_ab covers the tier-1 shape
def test_steal_segmented_ab_mixed_windows():
    # gemm(24) on 4 devices: template and sort branches side by side (the
    # test_parallel mixed-window shape) — both kernels, both = engine
    cfg = SamplerConfig(cls=8)
    spec = gemm(24)
    want = run(spec, cfg)
    mesh = default_mesh(4)
    seg = shard_run(spec, cfg, mesh=mesh, dispatch="steal", segmented=True)
    leg = shard_run(spec, cfg, mesh=mesh, dispatch="steal", segmented=False)
    assert_same(want, seg, "segmented")
    assert_same(want, leg, "legacy kernel")


def test_shard_static_segmented_ab():
    # the static shard_map program rides the segmented kernel too; the
    # legacy ghost-merge stays available for A/B
    cfg = SamplerConfig()
    spec = REGISTRY["syrk"](32)
    want = run(spec, cfg)
    mesh = default_mesh(4)
    for segmented in (True, False):
        got = shard_run(spec, cfg, mesh=mesh, dispatch="static",
                        segmented=segmented)
        assert_same(want, got, f"static segmented={segmented}")


@pytest.mark.slow  # sub-window carry rides tier-1 via
# test_parallel.py::test_shard_subwindows_dynamic_assignment_and_resume
def test_steal_quad_subwindows_and_resume():
    # forced sub-windows on a triangular nest: multi-window chunks carry
    # heads/tails across windows INSIDE a chunk and across chunks
    spec = REGISTRY["syrk_tri"](16)
    cfg = SamplerConfig()
    a = run(spec, cfg, window_accesses=1)
    b = shard_run(spec, cfg, mesh=default_mesh(2), window_accesses=1,
                  dispatch="steal")
    assert_same(a, b, "syrk_tri sub-windows")
    c = run(gemm(64), cfg, start_point=24)
    d = shard_run(gemm(64), cfg, mesh=default_mesh(2), start_point=24,
                  dispatch="steal", window_accesses=1)
    assert_same(c, d, "start_point resume")


def test_steal_share_cap_retry_iterative():
    """The share-cap overflow retry is a LOOP, not recursion: a cap of 1
    converges through doubling attempts without touching the recursion
    limit, bit-identical to the engine (and lands the retry counter)."""
    import sys

    from pluss import obs

    spec = gemm(16)
    cfg = SamplerConfig(cls=8)
    want = run(spec, cfg)
    old = sys.getrecursionlimit()
    tel = obs.active()
    try:
        sys.setrecursionlimit(120)   # deep retry recursion would die here
        got = shard_run(spec, cfg, share_cap=1, mesh=default_mesh(2),
                        dispatch="steal")
    finally:
        sys.setrecursionlimit(old)
    assert got.max_iteration_count == want.max_iteration_count
    assert (got.noshare_dense == want.noshare_dense).all()
    assert got.share_list() == want.share_list()
    if tel is not None:
        assert obs.counters().get("engine.share_cap_retries", 0) >= 1


def test_steal_counters_and_busy_gauges_land(tmp_path):
    from pluss import obs

    obs.configure(str(tmp_path / "t.jsonl"))
    try:
        shard_run(gemm(16), SamplerConfig(cls=8), mesh=default_mesh(4),
                  dispatch="steal")
        c, tel = obs.counters(), obs.active()
        g = tel.gauges()
        assert c.get("shard.chunks", 0) >= 1
        assert "shard.steals" in c
        assert any(k.startswith("shard.device_busy_frac.") for k in g)
    finally:
        obs.configure(None)


@pytest.mark.slow
def test_steal_all_registry_families_all_device_counts():
    """Acceptance sweep: every registry family, D in {1, 2, 4, 8}, steal
    dispatch ≡ engine.run bit-for-bit (D=1 is the engine-delegation
    path).  Slow: full tier-2 coverage; tier-1 carries the 3-family
    subset above."""
    cfg = SamplerConfig()
    for name in sorted(REGISTRY):
        spec = REGISTRY[name]()
        want = run(spec, cfg)
        for n_dev in (1, 2, 4, 8):
            got = shard_run(spec, cfg, mesh=default_mesh(n_dev),
                            dispatch="steal" if n_dev > 1 else None)
            assert_same(want, got, f"{name} D={n_dev}")


# ---------------------------------------------------------------------------
# streamed sharded replay through the queue dispatcher


def _write_trace(path, lines, shift=6):
    (np.asarray(lines, np.uint64) << np.uint64(shift)).astype(
        "<u8").tofile(path)


def test_trace_steal_matches_replay_file(tmp_path):
    from pluss import trace

    rng = np.random.default_rng(11)
    p = str(tmp_path / "t.bin")
    _write_trace(p, rng.integers(0, 5000, 40_000, dtype=np.int64))
    window = 1 << 9
    a = trace.replay_file(p, window=window)
    b = trace.shard_replay_file(p, window=window, batch_windows=2,
                                dispatch="steal")
    assert a.hist.tolist() == b.hist.tolist()
    assert a.total_count == b.total_count


def test_trace_steal_sparse_clusters_and_ragged_tail(tmp_path):
    # compactor growth mid-stream (chunks at pre-growth capacities merge
    # against the final table) + a tail chunk shorter than the chunk size
    from pluss import trace

    rng = np.random.default_rng(12)
    p = str(tmp_path / "t.bin")
    _write_trace(p, np.concatenate([
        rng.integers(0, 4096, 20_000, dtype=np.int64),
        (1 << 40) + rng.integers(0, 4096, 12_345, dtype=np.int64)]))
    a = trace.replay_file(p, window=1 << 9)
    b = trace.shard_replay_file(p, window=1 << 9, batch_windows=3,
                                dispatch="steal")
    assert a.hist.tolist() == b.hist.tolist()


@pytest.mark.slow  # checkpoint/resume identity rides tier-1 via
# test_trace.py::test_shard_replay_file_resume_checkpoint
def test_trace_checkpoint_pins_static_dispatch(tmp_path, capsys):
    # checkpointing identity IS the static segment grid: an explicit
    # steal request downgrades with a notice instead of mis-checkpointing
    from pluss import trace

    rng = np.random.default_rng(13)
    p = str(tmp_path / "t.bin")
    _write_trace(p, rng.integers(0, 3000, 20_000, dtype=np.int64))
    ck = str(tmp_path / "ck")
    a = trace.replay_file(p, window=1 << 9)
    b = trace.shard_replay_file(p, window=1 << 9, batch_windows=2,
                                dispatch="steal", checkpoint_path=ck)
    assert a.hist.tolist() == b.hist.tolist()
    assert "static" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# device-group sweep: parallel == serial, elastic requeue on worker death


@pytest.mark.slow  # tier-1 keeps test_sweep_elastic_requeue_on_worker_death
# as the device-group sweep representative
def test_sweep_device_groups_matches_serial():
    from pluss import sweep as sweep_mod

    spec = gemm(16)
    a = sweep_mod.sweep(spec, (1, 2, 4), (2, 4), SamplerConfig())
    b = sweep_mod.sweep(spec, (1, 2, 4), (2, 4), SamplerConfig(),
                        device_groups=4)
    for pa, pb in zip(a, b):
        assert pa.cfg == pb.cfg
        assert pa.curve.tolist() == pb.curve.tolist()
        assert pa.total_refs == pb.total_refs


def test_sweep_elastic_requeue_on_worker_death(tmp_path, monkeypatch):
    import pluss.parallel.shard as shard_mod
    import pluss.resilience as res_mod
    from pluss import obs, sweep as sweep_mod
    from pluss.resilience.errors import PlussError

    real_rr = res_mod.run_resilient
    real_sr = shard_mod.shard_run
    died = {"n": 0}

    def die_once(cfg):
        # FATAL (neither retryable nor degradable): the ladder re-raises
        # it, so recovery must come from the sweep's elastic requeue —
        # exactly the worker-death shape
        if cfg.thread_num == 2 and died["n"] == 0:
            died["n"] += 1
            raise PlussError("injected worker death", site="test.sweep")

    def flaky_rr(spec, cfg, share_cap, **kw):
        die_once(cfg)
        return real_rr(spec, cfg, share_cap, **kw)

    def flaky_sr(spec, cfg=None, share_cap=None, *a, **kw):
        die_once(cfg)
        return real_sr(spec, cfg, share_cap, *a, **kw)

    # a point runs run_resilient (1-device group) or shard_run (multi-
    # device group) depending on the device split — inject into both
    monkeypatch.setattr(res_mod, "run_resilient", flaky_rr)
    monkeypatch.setattr(shard_mod, "shard_run", flaky_sr)
    obs.configure(str(tmp_path / "t.jsonl"))
    try:
        spec = gemm(16)
        j = str(tmp_path / "j.jsonl")
        pts = sweep_mod.sweep(spec, (1, 2, 4), (2,), SamplerConfig(),
                              journal=j, device_groups=2)
        c = obs.counters()
    finally:
        obs.configure(None)
    assert died["n"] == 1, "the injected death never fired"
    assert c.get("sweep.elastic_requeues", 0) >= 1
    clean = sweep_mod.sweep(spec, (1, 2, 4), (2,), SamplerConfig())
    for pa, pb in zip(clean, pts):
        assert pa.curve.tolist() == pb.curve.tolist()


# ---------------------------------------------------------------------------
# stats block + README sync


def test_stats_shard_breakdown_render():
    from pluss.obs.stats import shard_breakdown

    counters = {"shard.chunks": 24.0, "shard.steals": 3.0,
                "engine.share_cap_retries": 1.0}
    gauges = {"shard.device_busy_frac.0": 0.91,
              "shard.device_busy_frac.1": 0.88}
    lines = shard_breakdown(counters, gauges)
    assert lines[0] == "shard scale-out:"
    text = "\n".join(lines)
    assert "chunks dispatched" in text and "24" in text
    assert "chunks stolen" in text and "12.5%" in text
    assert "d0=0.91" in text and "d1=0.88" in text
    assert "share-cap retries" in text
    assert shard_breakdown({}, {}) == []


def test_readme_scaleout_section_in_sync():
    """README's Scale-out section must name every dispatch knob and every
    telemetry name the steal path emits — the test-synced-docs discipline
    the other README sections follow."""
    import os

    readme = open(os.path.join(os.path.dirname(__file__), os.pardir,
                               "README.md")).read()
    assert "## Scale-out" in readme, "README Scale-out section missing"
    for needle in (
            "PLUSS_SHARD_DISPATCH", "PLUSS_SHARD_SEGMENTED",
            "PLUSS_SHARD_CHUNK_WINDOWS", "PLUSS_SHARD_STEAL_SEED",
            "PLUSS_SHARD_STEAL_MIN_REFS",
            "--shard-dispatch", "--device-groups",
            "shard.chunks", "shard.steals", "shard.device_busy_frac",
            "shard scale-out:",
            "scaling_efficiency", "multichip_refs_per_sec",
    ):
        assert needle in readme, f"README Scale-out out of sync: {needle}"


@pytest.mark.slow   # run.sh executes the real gate; the wrapper re-runs it
def test_multichip_smoke_wrapper():
    """The run.sh multichip gate, as a pytest (small sizes)."""
    from pluss import multichip_smoke

    multichip_smoke.smoke(trace_refs=60_000, window=1 << 11, nest_n=12)
