"""Footprint prover tests: the cold-miss identity and the MRC bracket.

Two machine-checkable oracles pinned here:

1. **Cold identity** — the schedule-aware per-thread footprint equals
   the dynamic cold-miss counts exactly: against the pure-Python oracle
   for EVERY registry model (several schedules), and against the live
   engine for a representative slice including quadratic-contract nests.
2. **MRC bracket** — the sampled (CRI + AET) curve's terminal plateau
   has exactly the static floor value (T=1) and flattens inside the
   static ``[c_lo, c_hi]`` location bracket, on gemm + two stencils and
   on every quadratic-contract nest in the registry (the acceptance
   criterion).
"""

from __future__ import annotations

import numpy as np
import pytest

from pluss import cri, engine, mrc
from pluss.analysis import footprint
from pluss.config import SamplerConfig
from pluss.models import REGISTRY
from pluss.spec import nest_has_inner_bounds
from tests.oracle import OracleSampler

#: registry families whose nests use the quadratic position contract
QUAD_MODELS = sorted(
    name for name in REGISTRY
    if any(nest_has_inner_bounds(nest) for nest in REGISTRY[name](8).nests)
)


def test_quad_models_exist():
    # the bracket acceptance criterion quantifies over these — the list
    # must not silently go empty if models are reshuffled
    assert QUAD_MODELS


# ---------------------------------------------------------------------------
# cold identity vs the oracle, every registry model
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(REGISTRY))
def test_predicted_cold_matches_oracle(name):
    spec = REGISTRY[name](8)
    for T, CS in [(1, 4), (2, 2), (3, 2)]:
        cfg = SamplerConfig(thread_num=T, chunk_size=CS)
        o = OracleSampler(spec, cfg).run()
        oracle_cold = np.array([o.noshare[t].get(-1, 0.0)
                                for t in range(T)], np.int64)
        np.testing.assert_array_equal(
            footprint.predicted_cold(spec, cfg), oracle_cold,
            err_msg=f"{name} T={T} CS={CS}")
        fp = footprint.footprints(spec, cfg)
        assert fp.accesses == o.max_iteration_count
        assert int(fp.per_thread_accesses.sum()) == o.max_iteration_count


# ---------------------------------------------------------------------------
# cold identity vs the live engine (incl. quadratic-contract nests)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ["gemm", "syrk_tri", "jacobi2d",
                                  "stencil3d"] + QUAD_MODELS)
def test_predicted_cold_matches_engine(name):
    spec = REGISTRY[name](8)
    cfg = SamplerConfig(thread_num=2, chunk_size=2)
    res = engine.run(spec, cfg)
    np.testing.assert_array_equal(footprint.predicted_cold(spec, cfg),
                                  res.noshare_dense[:, 0])
    assert footprint.footprints(spec, cfg).accesses == \
        res.max_iteration_count


# ---------------------------------------------------------------------------
# MRC bracket vs the sampled curve
# ---------------------------------------------------------------------------

def _sampled_curve(spec, cfg):
    res = engine.run(spec, cfg)
    ri = cri.distribute(res.noshare_list(), res.share_list(),
                        cfg.thread_num)
    return mrc.aet_mrc(ri, cfg)


def _plateau_start(curve, floor, eps=1e-9):
    above = np.nonzero(curve > floor + eps)[0]
    return int(above[-1]) + 1 if len(above) else 0


def _assert_bracket(spec, cfg):
    curve = _sampled_curve(spec, cfg)
    br = footprint.mrc_bracket(spec, cfg)
    # the static floor is a true lower bound for any T …
    assert float(curve.min()) >= br.floor - 1e-9
    pl = _plateau_start(curve, br.floor)
    assert br.c_lo <= pl <= br.c_hi, (
        f"plateau {pl} outside static bracket [{br.c_lo}, {br.c_hi}]")
    if cfg.thread_num == 1 and len(curve) > br.c_hi:
        # … and EXACT at T=1 (no CRI dilation): by c_hi the curve must
        # have flattened onto precisely the cold fraction
        np.testing.assert_allclose(curve[br.c_hi:], br.floor, rtol=1e-9)
    return br


#: gemm + two stencils (the ISSUE's bracket-property floor) at element
#: granularity, where the guaranteed-reuse lower bound has teeth
_BRACKET_MODELS = ["gemm", "jacobi2d", "stencil3d"]


@pytest.mark.parametrize("name", _BRACKET_MODELS)
def test_bracket_T1_element_granular(name):
    spec = REGISTRY[name](8)
    br = _assert_bracket(spec, SamplerConfig(thread_num=1, chunk_size=2,
                                             cls=8, ds=8))
    if name == "gemm":
        # A is a single-ref invariant array: the guaranteed closed-form
        # reuse exists and pushes c_lo off the trivial zero
        assert br.guaranteed_reuse > 0 and br.c_lo > 0


@pytest.mark.parametrize("name", _BRACKET_MODELS)
def test_bracket_T1_line_granular(name):
    _assert_bracket(REGISTRY[name](8),
                    SamplerConfig(thread_num=1, chunk_size=2))


@pytest.mark.parametrize("name", QUAD_MODELS)
def test_bracket_quad_contract_nests(name):
    # the acceptance criterion: static footprint bounds bracket the
    # sampled MRC inflection for ALL quadratic-contract nests
    spec = REGISTRY[name](8)
    _assert_bracket(spec, SamplerConfig(thread_num=1, chunk_size=2,
                                        cls=8, ds=8))
    _assert_bracket(spec, SamplerConfig(thread_num=1, chunk_size=2))


@pytest.mark.parametrize("name", _BRACKET_MODELS + QUAD_MODELS)
def test_bracket_T2_dilated(name):
    # under CRI dilation the floor stays a lower bound and the location
    # bracket still holds (c_hi carries the dilation factor + NBD tail)
    _assert_bracket(REGISTRY[name](8),
                    SamplerConfig(thread_num=2, chunk_size=2, cls=8, ds=8))


def test_guaranteed_reuse_key_is_real():
    # the guaranteed reuse must appear in the oracle's noshare histogram
    # (that is what makes c_lo sound): gemm's A at element granularity
    spec = REGISTRY["gemm"](8)
    cfg = SamplerConfig(thread_num=1, chunk_size=2, cls=8, ds=8)
    t_g = footprint.guaranteed_reuse(spec, cfg)
    assert t_g > 0
    o = OracleSampler(spec, cfg).run()
    key = 1 << (t_g.bit_length() - 1)
    merged = {}
    for h in o.noshare:
        for k, v in h.items():
            merged[k] = merged.get(k, 0) + v
    assert merged.get(key, 0) > 0


def test_level_bounds_are_ordered_and_cover_arrays():
    spec = REGISTRY["gemm"](16)
    fp = footprint.footprints(spec, SamplerConfig(thread_num=2,
                                                  chunk_size=2))
    assert fp.levels
    for lv in fp.levels:
        assert 0 <= lv.lines_lo <= lv.lines_hi
    # one whole parallel iteration touches at most the global footprint
    depth0 = [lv for lv in fp.levels if lv.depth == 0]
    assert depth0 and all(lv.lines_lo <= fp.total for lv in depth0)
