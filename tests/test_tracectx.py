"""Observability-plane tests (r20): request trace-context propagation
(threading.local + capture/attach handoff), trace-stamped telemetry,
observable passivity (traced serve bit-identical to untraced), the live
metrics plane (/metrics endpoint + {"op": "metrics"} verb), the SLO
burn-rate monitor, the crash flight recorder, and the `pluss stats`
--trace / --follow readers."""

import io
import json
import threading
import time
import urllib.request

import pytest

import tests.conftest  # noqa: F401  (CPU platform + x64)
from pluss import obs
from pluss.obs import stats as stats_mod
from pluss.obs import tracectx
from pluss.obs.flight import FlightRecorder
from pluss.obs.slo import SloMonitor
from pluss.obs.telemetry import render_prom
from pluss.serve import Client, ServeConfig, Server


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.shutdown()
    yield
    obs.shutdown()


def _events(path):
    recs, problems, notes = stats_mod.load(str(path))
    assert problems == [], problems
    return recs


# ---------------------------------------------------------------------------
# tracectx primitives


def test_bind_nests_and_restores():
    assert tracectx.current() is None
    with tracectx.bind("r1"):
        assert tracectx.current() == "r1"
        with tracectx.bind("r2"):
            assert tracectx.current() == "r2"
        assert tracectx.current() == "r1"
    assert tracectx.current() is None


def test_bind_none_is_noop():
    with tracectx.bind(None):
        assert tracectx.current() is None
    with tracectx.bind("r1"), tracectx.bind(None):
        assert tracectx.current() == "r1"


def test_capture_attach_crosses_threads():
    got = {}

    def worker(token):
        with tracectx.attach(token):
            got["inner"] = tracectx.current()
        got["after"] = tracectx.current()

    with tracectx.bind("r-x"):
        t = threading.Thread(target=worker, args=(tracectx.capture(),))
        t.start()
        t.join()
    assert got == {"inner": "r-x", "after": None}


def test_feed_pool_workers_inherit_context():
    """The _FeedPool handoff: workers run read/compact/encode under the
    submitting thread's trace context (captured at construction)."""
    from pluss.trace import _FeedPool

    seen = []
    with tracectx.bind("r-feed"):
        pool = _FeedPool(0, 3, claim_fn=lambda b: None,
                         read_fn=lambda b: seen.append(tracectx.current()),
                         compact_fn=lambda b, raw: raw,
                         encode_fn=lambda b, mid: b, workers=2, depth=2)
    with pool:
        assert list(pool) == [0, 1, 2]
    assert seen == ["r-feed"] * 3


def test_disabled_trace_event_micro_bound():
    """PR-5 discipline: with telemetry disabled AND no bound context the
    hook must stay a None-check no-op."""
    assert not obs.enabled()
    t0 = time.perf_counter()
    for _ in range(200_000):
        obs.trace_event("serve.admit", kind="spec")
    assert time.perf_counter() - t0 < 1.0


def test_trace_event_needs_bound_context(tmp_path):
    ev = tmp_path / "ev.jsonl"
    obs.configure(str(ev))
    obs.trace_event("unbound.event", x=1)      # no context: dropped
    with tracectx.bind("r-1"):
        obs.trace_event("bound.event", x=2)
        with obs.span("bound.span"):
            pass
    obs.shutdown()
    recs = _events(ev)
    names = [r.get("name") for r in recs]
    assert "unbound.event" not in names
    evr = next(r for r in recs if r.get("name") == "bound.event")
    spr = next(r for r in recs if r.get("name") == "bound.span")
    assert evr["trace"] == "r-1" and spr["trace"] == "r-1"


# ---------------------------------------------------------------------------
# traced serve: passivity + linkage


@pytest.fixture
def server_factory(tmp_path):
    servers = []
    counter = [0]

    def build(**cfg_kw) -> Server:
        counter[0] += 1
        sock = str(tmp_path / f"s{counter[0]}.sock")
        srv = Server(socket_path=sock, config=ServeConfig(**cfg_kw))
        srv.start()
        servers.append(srv)
        return srv

    yield build
    for srv in servers:
        srv.shutdown(drain_timeout_s=30)


_REQ = {"model": "gemm", "n": 16, "threads": 2, "chunk": 2,
        "output": "both"}


def test_traced_serve_bit_identical_to_untraced(server_factory, tmp_path):
    srv = server_factory(max_batch=4)
    with Client(srv.socket_path) as c:
        untraced = c.request(dict(_REQ, id="u-1"))
    obs.configure(str(tmp_path / "ev.jsonl"))
    srv2 = server_factory(max_batch=4)
    with Client(srv2.socket_path) as c:
        traced = c.request(dict(_REQ, id="t-1"))
    assert untraced["ok"] and traced["ok"]
    assert traced["mrc"] == untraced["mrc"]
    assert traced["histogram"] == untraced["histogram"]


def test_traced_request_span_tree(server_factory, tmp_path):
    ev = tmp_path / "ev.jsonl"
    obs.configure(str(ev))
    srv = server_factory(max_batch=4)
    with Client(srv.socket_path) as c:
        r = c.request(dict(_REQ, id="r-tree"))
    assert r["ok"]
    # the reply is sent from INSIDE serve.batch (via serve.demux); drain
    # the server first so the batch span's exit record lands in the stream
    srv.shutdown(drain_timeout_s=30)
    obs.shutdown()
    buf = io.StringIO()
    rc = stats_mod.main(str(ev), buf, io.StringIO(), trace="r-tree")
    tree = buf.getvalue()
    assert rc == 0
    for needle in ("trace r-tree:", "admission.verdict", "serve.admit",
                   "serve.queue_wait", "serve.batch", "serve.demux"):
        assert needle in tree, f"missing {needle!r}:\n{tree}"


def test_coalesced_batch_links_member_rids(server_factory, tmp_path):
    """One shared dispatch serving N requests records EVERY member rid:
    the batch span's ``traces`` attr links them, and `stats --trace`
    resolves the batch for each member."""
    ev = tmp_path / "ev.jsonl"
    obs.configure(str(ev))
    srv = server_factory(max_batch=8, max_delay_ms=10, max_queue=32)
    with Client(srv.socket_path) as hold:
        hid = hold.send({"sleep_ms": 500})
        time.sleep(0.15)
        with Client(srv.socket_path) as c:
            ids = [c.send(dict(_REQ, id=f"co-{i}")) for i in range(3)]
            rs = [c.recv(i) for i in ids]
        hold.recv(hid)
    assert all(r["ok"] for r in rs)
    assert any(r.get("batched", 1) > 1 for r in rs), \
        "hold did not force coalescing"
    srv.shutdown(drain_timeout_s=30)   # let serve.batch spans exit
    obs.shutdown()
    recs = _events(ev)
    batch = [r for r in recs if r.get("name") == "serve.batch"
             and len(r.get("attrs", {}).get("traces", [])) > 1]
    assert batch, "no multi-member serve.batch span recorded"
    members = set(batch[-1]["attrs"]["traces"])
    assert members <= {f"co-{i}" for i in range(3)} and len(members) > 1
    # every member resolves the shared batch span via --trace
    for rid in members:
        buf = io.StringIO()
        assert stats_mod.main(str(ev), buf, io.StringIO(),
                              trace=rid) == 0
        assert "serve.batch" in buf.getvalue()
    coal = [r for r in recs if r.get("name") == "serve.coalesced"]
    assert coal and set(coal[-1]["attrs"]["traces"]) == members


# ---------------------------------------------------------------------------
# live metrics plane


def test_metrics_endpoint_and_verb(server_factory):
    srv = server_factory(max_batch=4, metrics_port=0)
    assert srv.metrics_port
    with Client(srv.socket_path) as c:
        assert c.request(dict(_REQ, id="m-1"))["ok"]
        verb = c.request({"op": "metrics"})
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.metrics_port}/metrics",
                timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        # unknown paths 404
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.metrics_port}/nope", timeout=10)
            assert False, "bad path did not 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    assert verb["ok"]
    for t in (text, verb["text"]):
        assert "# TYPE pluss_serve_requests_spec counter" in t
        assert "# HELP pluss_serve_requests_spec" in t
        assert "pluss_serve_ok" in t


def test_render_prom_hygiene():
    text = render_prom({"serve.ok": 3, "bad-name!x": 1},
                       {"queue.depth": 2.5},
                       {"serve.latency_ms": {"0.9": 4.0, "0.5": 2.0,
                                             "0.99": None}})
    lines = text.splitlines()
    assert "# TYPE pluss_serve_ok counter" in lines
    assert "# HELP pluss_serve_ok pluss cumulative counter serve.ok" \
        in lines
    assert "pluss_serve_ok 3" in lines
    assert "# TYPE pluss_queue_depth gauge" in lines
    assert "pluss_bad_name_x 1" in lines          # label-safe sanitization
    i50 = lines.index('pluss_serve_latency_ms{quantile="0.5"} 2')
    i90 = lines.index('pluss_serve_latency_ms{quantile="0.9"} 4')
    assert i50 < i90                              # sorted by quantile
    assert not any("0.99" in ln for ln in lines)  # None skipped
    assert "# TYPE pluss_serve_latency_ms summary" in lines


# ---------------------------------------------------------------------------
# SLO burn-rate monitor


def _clock(t0=[0.0]):
    pass


def test_slo_burn_math_and_volume_gate():
    now = [1000.0]
    m = SloMonitor(target=0.1, fast_s=60, slow_s=600, burn_fast=2.0,
                   burn_slow=1.0, min_count=10, clock=lambda: now[0])
    for _ in range(4):
        m.record(ok=False)
    # 100% bad at 10% target = burn 10 — but only 4 outcomes: gated
    assert m.burn(m.fast_s) == pytest.approx(10.0)
    assert not m.burning_fast()
    for _ in range(6):
        m.record(ok=True)
    assert m.burn(m.fast_s) == pytest.approx(4.0)   # 40% bad / 0.1
    assert m.burning_fast()                          # >= 2.0, volume ok
    now[0] += 700.0                                  # everything ages out
    assert m.burn(m.fast_s) == 0.0 and not m.burning_fast()


def test_slo_transition_events_only(tmp_path):
    ev = tmp_path / "ev.jsonl"
    obs.configure(str(ev))
    now = [2000.0]
    m = SloMonitor(target=0.1, fast_s=60, slow_s=60, burn_fast=2.0,
                   burn_slow=2.0, min_count=4, clock=lambda: now[0])
    for _ in range(8):
        m.record(ok=False)   # burning from the 4th outcome on
    for _ in range(40):
        m.record(ok=True)    # recovers once the rate dilutes under 0.2
    obs.shutdown()
    burns = [r for r in _events(ev) if r.get("name") == "slo.burn"]
    fast = [r for r in burns if r["attrs"]["window"] == "fast"]
    # transition-only: one burning, one recovered — not one per record
    assert [r["attrs"]["state"] for r in fast] == ["burning", "recovered"]


def test_slo_health_and_ready_gate(server_factory):
    srv = server_factory(max_batch=4)
    with Client(srv.socket_path) as c:
        assert c.request(dict(_REQ, id="s-1"))["ok"]
        h = c.request({"op": "health"})
        assert "slo_burn_fast" in h and "slo_burn_slow" in h
        rd = c.request({"op": "ready"})
        assert rd["ready"]
    # force the monitor over threshold with volume: readiness names SLO
    srv.slo.min_count = 10
    for _ in range(50):
        srv.slo.record(ok=False)
    with Client(srv.socket_path) as c:
        rd = c.request({"op": "ready"})
    assert not rd["ready"] and any("slo" in s for s in rd["reasons"])


# ---------------------------------------------------------------------------
# flight recorder


def test_flight_dump_passes_stats_check(tmp_path):
    fr = FlightRecorder(out_dir=str(tmp_path), ring=64, throttle_s=0.0)
    fr.arm()
    try:
        with tracectx.bind("r-boom"):
            with obs.span("serve.batch", size=1):
                obs.trace_event("residency.consult", outcome="miss")
        path = fr.dump("dispatch_error", rid="r-boom")
    finally:
        fr.disarm()
    assert path and path.endswith("flight-r-boom.jsonl")
    rc = stats_mod.main(path, io.StringIO(), io.StringIO(), check=True)
    assert rc == 0, "flight dump failed stats --check"
    recs = [json.loads(ln) for ln in open(path)]
    assert recs[0]["flight_reason"] == "dispatch_error"
    assert recs[0]["flight_trace"] == "r-boom"
    assert any(r.get("name") == "serve.batch" and r.get("trace") == "r-boom"
               for r in recs)
    assert not any(r.get("ev") == "end" for r in recs)
    # --trace works on the dump too
    buf = io.StringIO()
    assert stats_mod.main(path, buf, io.StringIO(), trace="r-boom") == 0
    assert "serve.batch" in buf.getvalue()


def test_flight_ring_bounded_and_throttled(tmp_path):
    fr = FlightRecorder(out_dir=str(tmp_path), ring=16, throttle_s=60.0)
    fr.arm()
    try:
        with tracectx.bind("r-ring"):
            for i in range(100):
                obs.trace_event("tick", i=i)
        p1 = fr.dump("watchdog_abandon", rid="a")
        p2 = fr.dump("watchdog_abandon", rid="b")   # throttled
        p3 = fr.dump("breaker_open", rid="c")       # distinct reason: ok
    finally:
        fr.disarm()
    assert p1 and p3 and p2 is None
    body = [json.loads(ln) for ln in open(p1)][1:]
    ticks = [r for r in body if r.get("name") == "tick"]
    assert len(ticks) == 16                          # ring cap held
    assert ticks[-1]["attrs"]["i"] == 99             # newest survive


def test_flight_memory_only_until_dump(tmp_path, monkeypatch):
    """Arming with telemetry disabled creates a memory-only session:
    zero bytes anywhere until a dump fires."""
    from pluss.obs import telemetry

    monkeypatch.chdir(tmp_path)
    assert not obs.enabled()
    fr = FlightRecorder(out_dir=str(tmp_path), ring=32)
    fr.arm()
    try:
        # memory-only sessions still count as enabled() — the taps need
        # to see records — but no sink path means zero bytes on disk
        assert telemetry.configured()
        obs.counter_add("serve.ok")
        with tracectx.bind("r-m"):
            obs.trace_event("serve.admit", kind="spec")
        assert list(tmp_path.iterdir()) == []
        path = fr.dump("drain_forced")
    finally:
        fr.disarm()
        telemetry.shutdown()
    assert path
    recs = [json.loads(ln) for ln in open(path)]
    assert any(r.get("name") == "serve.admit" for r in recs)
    assert any(r.get("name") == "serve.ok" and r.get("ev") == "counter"
               for r in recs)


def test_server_owns_flight_session_no_counter_leak(server_factory):
    """An embedded server on a disabled-telemetry process must tear its
    memory-only flight session down at shutdown (no cross-test leak)."""
    from pluss.obs import telemetry

    srv = server_factory(max_batch=2)
    with Client(srv.socket_path) as c:
        assert c.request(dict(_REQ, id="f-1"))["ok"]
    srv.shutdown(drain_timeout_s=30)
    assert not telemetry.configured()


# ---------------------------------------------------------------------------
# stats readers: --trace rendering and --follow tailing


def test_render_trace_nests_spans_and_events():
    recs = [
        {"ev": "span", "name": "serve.batch", "id": 1, "t": 1.0,
         "dur": 2.0, "trace": "r0", "attrs": {"traces": ["r0", "r1"]}},
        {"ev": "span", "name": "serve.demux", "id": 2, "parent": 1,
         "t": 2.5, "dur": 0.1, "trace": "r1"},
        {"ev": "event", "name": "serve.admit", "t": 0.5, "trace": "r1"},
        {"ev": "span", "name": "unrelated", "id": 3, "t": 0.1,
         "dur": 0.2, "trace": "zzz"},
    ]
    buf = io.StringIO()
    assert stats_mod.render_trace(recs, "r1", buf) == 0
    out = buf.getvalue()
    assert "trace r1:" in out and "unrelated" not in out
    # the demux child renders indented under the linked batch span
    batch_line = next(l for l in out.splitlines() if "serve.batch" in l)
    demux_line = next(l for l in out.splitlines() if "serve.demux" in l)
    assert demux_line.index("serve.demux") > batch_line.index("serve.batch")
    buf = io.StringIO()
    assert stats_mod.render_trace(recs, "nope", buf) == 1


def test_follow_tails_and_stops_at_end(tmp_path):
    ev = tmp_path / "ev.jsonl"
    lines = [
        {"ev": "meta", "schema": 1},
        {"ev": "event", "name": "serve.admit", "t": 0.1, "trace": "r0"},
        {"ev": "counter", "name": "serve.ok", "value": 1, "t": 0.2},
        {"ev": "end", "t": 0.3},
    ]
    done = threading.Event()

    def writer():
        with open(ev, "w") as f:
            for rec in lines:
                f.write(json.dumps(rec) + "\n")
                f.flush()
                time.sleep(0.05)
        done.set()

    t = threading.Thread(target=writer)
    t.start()
    buf = io.StringIO()
    rc = stats_mod.follow(str(ev), buf, io.StringIO(), poll_s=0.02,
                          max_idle_s=10.0)
    t.join()
    assert rc == 0 and done.is_set()
    out = buf.getvalue()
    assert "serve.admit" in out and "serve.ok" in out


def test_follow_missing_file_errors(tmp_path):
    rc = stats_mod.follow(str(tmp_path / "nope.jsonl"), io.StringIO(),
                          io.StringIO(), poll_s=0.01, max_idle_s=0.1)
    assert rc == 2


def test_cli_stats_flags(tmp_path, capsys):
    from pluss.cli import main as cli_main

    ev = tmp_path / "ev.jsonl"
    obs.configure(str(ev))
    with tracectx.bind("r-cli"):
        with obs.span("serve.batch"):
            pass
    obs.shutdown()
    assert cli_main(["stats", str(ev), "--trace", "r-cli"]) == 0
    assert "serve.batch" in capsys.readouterr().out
    rc = cli_main(["stats", str(ev), "--check"])
    assert rc == 0


# ---------------------------------------------------------------------------
# gates


@pytest.mark.slow   # run.sh executes the real gate; the wrapper re-runs it
def test_obsplane_smoke_wrapper():
    from pluss import obsplane_smoke

    assert obsplane_smoke.main() == 0


def test_readme_documents_observability_plane():
    with open("README.md", encoding="utf-8") as f:
        readme = f.read()
    for needle in (
            "--metrics-port", "/metrics", '{"op": "metrics"}',
            "PLUSS_SLO_TARGET", "PLUSS_SLO_FAST_S", "PLUSS_SLO_BURN_FAST",
            "PLUSS_SLO_MIN_COUNT", "PLUSS_FLIGHT_RING", "PLUSS_FLIGHT_DIR",
            "--flight-dir", "flight-", "slo.burn",
            "pluss stats", "--trace", "--follow", "serve.batch",
            "serve.demux", "admission.verdict", "serve.queue_wait",
            "trace context",
    ):
        assert needle in readme, f"README obs plane out of sync: {needle}"
