"""Warm-start execution layer (PR 11): persistent AOT executable
sidecars, the runtime salt, single-flight compilation, and the README
contract.

The load-bearing claim is BIT-IDENTITY: an executable restored from a
sidecar, a fresh in-process compile, and the plain JIT path
(``PLUSS_NO_AOT=1``) must produce byte-equal histograms and MRCs — the
warm-start layer is allowed to move compile seconds, never results.
"""

import os
import pickle
import threading

import numpy as np
import pytest

from pluss import cri, engine, mrc, obs, plancache, trace
from pluss.config import SamplerConfig
from pluss.models import REGISTRY


def _arm(tmp_path, monkeypatch):
    """Opt back into the disk plan cache (conftest disables it) with a
    fresh dir + telemetry sink, and start from cold in-process memos."""
    monkeypatch.delenv("PLUSS_NO_PLAN_CACHE", raising=False)
    monkeypatch.setenv("PLUSS_PLAN_CACHE_DIR", str(tmp_path / "cache"))
    obs.configure(str(tmp_path / "tel.jsonl"))
    engine.compiled.cache_clear()
    if not plancache.aot_supported():
        pytest.skip("backend cannot serialize executables")


def _mrc_of(res, cfg):
    ri = cri.distribute(res.noshare_list(), res.share_list(),
                        cfg.thread_num)
    return mrc.dedup_lines(mrc.aet_mrc(ri, cfg))


def _delta(c0, name):
    return obs.counters().get(name, 0) - c0.get(name, 0)


# ---------------------------------------------------------------------------
# bit-identity: restored executables == fresh compile == plain JIT


@pytest.mark.parametrize("model,n", [
    ("gemm", 16),        # template path — tier-1 representative
    pytest.param("syrk", 12, marks=pytest.mark.slow),    # interleave-overlay
    pytest.param("cholesky", 10, marks=pytest.mark.slow),  # quad nest
])
def test_aot_restore_bit_identical(tmp_path, monkeypatch, model, n):
    _arm(tmp_path, monkeypatch)
    spec, cfg = REGISTRY[model](n), SamplerConfig(thread_num=2,
                                                  chunk_size=2)
    ref = engine.run(spec, cfg)          # cold: compiles + writes sidecars
    assert list((tmp_path / "cache").glob("*.exe")), \
        "no AOT sidecar was persisted"

    engine.compiled.cache_clear()        # forget every in-process memo
    c0 = obs.counters()
    warm = engine.run(spec, cfg)         # must restore, not recompile
    assert _delta(c0, "engine.plan_cache.aot_hit") >= 1
    assert _delta(c0, "engine.compiles") == 0
    assert _delta(c0, "engine.compile_s") == 0

    monkeypatch.setenv("PLUSS_NO_AOT", "1")
    engine.compiled.cache_clear()
    jit = engine.run(spec, cfg)          # plain lazy-JIT ground truth

    for got, tag in ((warm, "restored"), (jit, "jit")):
        assert got.max_iteration_count == ref.max_iteration_count, tag
        assert got.noshare_list() == ref.noshare_list(), tag
        assert got.share_list() == ref.share_list(), tag
        assert _mrc_of(got, cfg) == _mrc_of(ref, cfg), tag


@pytest.mark.slow   # engine-path aot_restore covers the restore axis in tier-1
def test_trace_replay_aot_restore_bit_identical(tmp_path, monkeypatch):
    _arm(tmp_path, monkeypatch)
    # the replay-fn memo may hold executables resolved by EARLIER tests
    # (cache disabled then): start cold so the first replay saves sidecars
    trace._replay_fn_cached.cache_clear()
    refs_path = str(tmp_path / "refs.bin")
    rng = np.random.default_rng(7)
    rng.integers(0, 512, 20_000).astype("<u8").tofile(refs_path)

    r1 = trace.replay_file(refs_path, "u64", cls=16)
    assert list((tmp_path / "cache").glob("*.exe")), \
        "trace replay kernel persisted no sidecar"

    trace._replay_fn_cached.cache_clear()
    c0 = obs.counters()
    r2 = trace.replay_file(refs_path, "u64", cls=16)
    assert _delta(c0, "engine.plan_cache.aot_hit") >= 1
    assert _delta(c0, "engine.compiles") == 0

    monkeypatch.setenv("PLUSS_NO_AOT", "1")
    trace._replay_fn_cached.cache_clear()
    r3 = trace.replay_file(refs_path, "u64", cls=16)

    for got, tag in ((r2, "restored"), (r3, "jit")):
        np.testing.assert_array_equal(np.asarray(got.hist),
                                      np.asarray(r1.hist), err_msg=tag)
        assert got.histogram() == r1.histogram(), tag


# ---------------------------------------------------------------------------
# the runtime salt: sidecars pin the PJRT runtime, plan pickles do not


def test_runtime_salt_invalidates_sidecars_not_plans(tmp_path,
                                                     monkeypatch):
    _arm(tmp_path, monkeypatch)
    spec, cfg = REGISTRY["gemm"](16), SamplerConfig(thread_num=2,
                                                    chunk_size=2)
    ref = engine.run(spec, cfg)

    # a "jax upgrade": the runtime salt changes, the plan source does not
    engine.compiled.cache_clear()
    with monkeypatch.context() as m:
        m.setattr(plancache, "runtime_salt",
                  lambda: "jax=999.0/other/unknown/nbins=1")
        c0 = obs.counters()
        bumped = engine.run(spec, cfg)
        assert _delta(c0, "engine.plan_cache.aot_hit") == 0
        assert _delta(c0, "engine.compiles") >= 1, \
            "stale-runtime sidecar was not recompiled"
        assert _delta(c0, "engine.plan_cache.hit") >= 1, \
            "plan pickles must keep the cheaper source-only salt"
    assert bumped.noshare_list() == ref.noshare_list()

    # back on the original runtime the original sidecars still restore
    engine.compiled.cache_clear()
    c0 = obs.counters()
    engine.run(spec, cfg)
    assert _delta(c0, "engine.plan_cache.aot_hit") >= 1
    assert _delta(c0, "engine.compiles") == 0


def test_stale_payload_salt_is_a_miss_not_a_load(tmp_path, monkeypatch):
    # belt and braces: the salt is in the slot PATH and the PAYLOAD; a
    # well-formed sidecar whose payload carries another runtime's salt
    # (e.g. a hash collision or a copied cache dir) must read as a miss
    _arm(tmp_path, monkeypatch)
    engine.run(REGISTRY["gemm"](16),
               SamplerConfig(thread_num=2, chunk_size=2))
    side = sorted((tmp_path / "cache").glob("*.exe"))[0]
    payload = pickle.loads(side.read_bytes())
    side.write_bytes(pickle.dumps(("stale-runtime-salt",) + payload[1:]))
    c0 = obs.counters()
    assert plancache.aot_load(str(side)) is None
    assert _delta(c0, "engine.plan_cache.aot_miss") == 1
    assert _delta(c0, "engine.plan_cache.aot_load_fail") == 0


# ---------------------------------------------------------------------------
# sidecar hygiene: quarantine and group eviction, same as plan pickles


def test_corrupt_sidecar_quarantined_and_repaired(tmp_path, monkeypatch,
                                                  capsys):
    _arm(tmp_path, monkeypatch)
    spec, cfg = REGISTRY["gemm"](16), SamplerConfig(thread_num=2,
                                                    chunk_size=2)
    ref = engine.run(spec, cfg)
    cache = tmp_path / "cache"
    victim = sorted(cache.glob("*.exe"))[0]
    victim.write_bytes(b"\x00not a serialized executable")

    engine.compiled.cache_clear()
    c0 = obs.counters()
    again = engine.run(spec, cfg)
    assert _delta(c0, "engine.plan_cache.corrupt") >= 1
    assert _delta(c0, "engine.plan_cache.aot_load_fail") >= 1
    quarantined = list(cache.glob("*.corrupt"))
    assert quarantined, "bad sidecar bytes were not set aside"
    # the freed slot is repopulated: the NEXT process start is warm again
    assert victim.exists(), "recompile did not refill the sidecar slot"
    assert again.noshare_list() == ref.noshare_list()


def test_eviction_unlinks_sidecars_with_their_pickle(tmp_path,
                                                     monkeypatch):
    monkeypatch.delenv("PLUSS_NO_PLAN_CACHE", raising=False)
    cache = tmp_path / "cache"
    cache.mkdir()
    monkeypatch.setenv("PLUSS_PLAN_CACHE_DIR", str(cache))
    monkeypatch.setenv("PLUSS_PLAN_CACHE_MAX", "1")
    old, new = "a" * 32, "b" * 32
    for group, mtime in ((old, 1_000_000), (new, 2_000_000)):
        for name in (f"{group}.pkl", f"{group}.aot-{'0' * 16}.exe",
                     f"{group}.aot-{'1' * 16}.exe"):
            p = cache / name
            p.write_bytes(b"x")
            os.utime(p, (mtime, mtime))
    engine._plan_cache_evict()
    left = sorted(p.name for p in cache.iterdir())
    assert all(p.startswith(new) for p in left), left
    assert not any(p.startswith(old) for p in left), \
        "evicted group left an orphaned artifact"
    assert len(left) == 3   # the surviving group keeps ALL its members


# ---------------------------------------------------------------------------
# single-flight: N concurrent requests, one compile


def _fan_out(reg, key, build, n):
    results, errors = [None] * n, [None] * n

    def worker(i):
        try:
            results[i] = reg.do(key, build)
        except BaseException as e:  # noqa: BLE001 — collected for asserts
            errors[i] = e

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    return threads, results, errors


def _await_waiters(c0, n, timeout=10.0):
    """Block until n callers are parked on the in-flight build (the
    single-flight wait counter is bumped right before the park)."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if _delta(c0, "engine.compile_singleflight_waits") >= n:
            return
        time.sleep(0.01)
    raise AssertionError("waiters never queued on the in-flight build")


def test_single_flight_one_build_many_waiters(tmp_path):
    obs.configure(str(tmp_path / "tel.jsonl"))
    reg = plancache.CompileRegistry(gauge="engine.compile_inflight")
    release = threading.Event()
    builds = []

    def build():
        builds.append(threading.get_ident())
        release.wait(10)
        return object()

    c0 = obs.counters()
    threads, results, errors = _fan_out(reg, "k", build, 6)
    _await_waiters(c0, 5)
    assert reg.inflight() == 1
    release.set()
    for t in threads:
        t.join(10)
    assert len(builds) == 1, "concurrent callers duplicated the build"
    assert errors == [None] * 6
    assert all(r is results[0] for r in results), \
        "waiters did not share the leader's result"
    assert reg.inflight() == 0
    assert obs.gauges().get("engine.compile_inflight") == 0.0


def test_single_flight_failure_rejects_all_waiters_typed(tmp_path):
    obs.configure(str(tmp_path / "tel.jsonl"))
    reg = plancache.CompileRegistry()
    release = threading.Event()

    def build():
        release.wait(10)
        raise RuntimeError("injected compile failure")

    c0 = obs.counters()
    threads, results, errors = _fan_out(reg, "k", build, 6)
    _await_waiters(c0, 5)
    release.set()
    for t in threads:
        t.join(10)
    assert results == [None] * 6
    assert all(isinstance(e, RuntimeError) for e in errors)
    assert all(e is errors[0] for e in errors), \
        "waiters must get the leader's exception object, not a retry"
    # failures are never cached: the next cold caller builds fresh
    assert reg.do("k", lambda: "recovered") == "recovered"


# ---------------------------------------------------------------------------
# the README contract


def test_readme_documents_warm_start():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    text = open(os.path.join(here, "README.md")).read()
    for needle in ("Warm start", "PLUSS_XLA_CACHE_DIR", "--xla-cache",
                   "--warm", "PLUSS_NO_AOT", "aot_hit",
                   "serve.compile_inflight", "PLUSS_PLAN_CACHE_DIR"):
        assert needle in text, f"README lost the {needle!r} contract"
