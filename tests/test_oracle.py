"""Oracle self-checks against hand-derived golden histograms.

The GEMM-128 expectations below were derived analytically from the loop
structure (independent of both the oracle code and the reference):

Per (c0,c1) body = C0,C1,(A0,B0,C2,C3)x128 = 514 accesses; each thread serves
32 c0 values (8 chunks of 4); stream positions depend on thread-local rank only.

- C line (c0*16 + c1/8): C0 reuse 1 (112/c0), C1 reuse 1 (128/c0),
  C2 reuse 3 (16384/c0), C3 reuse 1 (16384/c0), cold 16/c0.
- A line (c0*16 + c2/8): reuse 4 for k%8!=0 (14336/c0), reuse 486 -> bin 256
  for k%8==0 at c1>0 (2032/c0), cold 16/c0.
- B line (c2*16 + c1/8): reuse 514 -> bin 512 for c1%8!=0 (14336/c0);
  c1%8==0 reuses cross a whole c1 loop: 62194 = 65792-7*514, share
  (2*62194 > 16513), 2048 per c0 for thread-local rank>0; 2048 cold lines
  per thread at rank 0.

Totals (4 threads x 32 c0): noshare {-1:12288, 1:2127872, 2:2097152,
4:1835008, 256:260096, 512:1835008}, share {62194:253952}, and
12288 + sum(emits) = 8421376 accesses ("max iteration traversed",
gemm_sampler.rs:305).
"""

import math

import pytest

from pluss.config import SamplerConfig
from pluss.models import gemm
from tests.oracle import (
    OracleSampler,
    aet_mrc,
    cri_distribute,
    cri_nbd,
    merge_noshare,
    merge_share,
    mrc_dedup_lines,
    nbd_pmf,
    to_highest_power_of_two,
)

GOLD_NOSHARE_128 = {
    -1: 12288.0,
    1: 2127872.0,
    2: 2097152.0,
    4: 1835008.0,
    256: 260096.0,
    512: 1835008.0,
}
GOLD_SHARE_128 = {62194: 253952.0}


def test_power_of_two_binning():
    assert [to_highest_power_of_two(x) for x in (1, 2, 3, 4, 5, 7, 8, 513, 514)] == [
        1, 2, 2, 4, 4, 4, 8, 512, 512,
    ]


@pytest.mark.slow
def test_gemm128_golden_histograms():
    o = OracleSampler(gemm(128)).run()
    assert o.max_iteration_count == 8421376
    assert merge_noshare(o.noshare) == GOLD_NOSHARE_128
    assert merge_share(o.share) == GOLD_SHARE_128
    # per-thread symmetry: every thread sees identical histograms
    for t in range(1, 4):
        assert o.noshare[t] == o.noshare[0]
        assert dict(o.share[t]) == dict(o.share[0])


def test_gemm8_counts():
    o = OracleSampler(gemm(8)).run()
    assert o.max_iteration_count == 8 * 8 * (2 + 4 * 8)
    # trip 8, chunk 4 -> 2 chunks -> threads 2,3 idle
    assert o.count[2] == 0 and o.count[3] == 0
    # N=8: every row is one cache line; C/A cold 4 lines per active thread, B 8
    assert o.noshare[0][-1] == 16.0
    assert merge_noshare(o.noshare)[-1] == 32.0
    assert merge_share(o.share) == {}


def test_gemm8_small_lines_produce_share():
    # CLS=DS makes every element its own line; B0 cross-c0 reuses become share
    cfg = SamplerConfig(cls=8)
    o = OracleSampler(gemm(8), cfg).run()
    share = merge_share(o.share)
    assert share, "expected share reuses with 1-element lines"
    span = 73  # (8+1)*8+1
    assert all(2 * r > span for r in share)


def test_nbd_pmf_matches_reference_parameterization():
    # NB(r=2, p=0.25): pmf(0) = 0.0625, pmf(1) = 2*0.25^2*0.75 = 0.09375
    assert math.isclose(nbd_pmf(0, 2.0, 0.25), 0.0625)
    assert math.isclose(nbd_pmf(1, 2.0, 0.25), 0.09375)
    assert math.isclose(nbd_pmf(2, 2.0, 0.25), 3 * 0.25**2 * 0.75**2)


def test_nbd_cutoff_point_mass():
    dist = {}
    cri_nbd(4, 3000, dist)  # 3000 >= 4000*3/4
    assert dist == {12000: 1.0}
    dist = {}
    cri_nbd(4, 2999, dist)
    assert len(dist) > 100  # a real dilation, not a point mass
    assert math.isclose(sum(dist.values()), 1.0, abs_tol=2e-4)
    assert min(dist) == 2999  # dist keys are k + n
    # mean of NB(r=n,p=1/4) is n(1-p)/p = 3n -> mass centered near 4n
    mean = sum(k * v for k, v in dist.items())
    assert abs(mean - 4 * 2999) < 100


def test_racetrack_residual_overwrite_semantics():
    # share {n=3: {10: 1.0}}, T=4 -> NBD dilates 10, each dilated ri split into
    # log2 bins; the last bin is OVERWRITten by 1-prob_sum (pluss_utils.h:1088)
    rihist = cri_distribute([{}], [{3: {10: 1.0}}], 4)
    assert all(k >= 0 for k in rihist)
    # mass for one dilated ri: 1 - prob_old_last != 1; total stays within (0, 1.2]
    total = sum(rihist.values())
    assert 0.5 < total < 1.2


def test_cri_noshare_mass_conserved():
    rihist = cri_distribute([{4: 100.0, -1: 7.0}], [{}], 4)
    assert rihist[-1] == 7.0
    positive = sum(v for k, v in rihist.items() if k >= 0)
    assert math.isclose(positive, 100.0, rel_tol=3e-4)
    assert min(k for k in rihist if k > 0) >= 4


def test_aet_mrc_monotone_and_bounded():
    rihist = {-1: 10.0, 1: 50.0, 4: 30.0, 64: 10.0}
    mrc = aet_mrc(rihist, cache_entries=327680)
    assert mrc[0] == 1.0
    vals = [mrc[c] for c in sorted(mrc)]
    assert all(0.0 <= v <= 1.0 for v in vals)
    assert all(a >= b - 1e-12 for a, b in zip(vals, vals[1:]))
    lines = mrc_dedup_lines(mrc)
    assert lines[0][0] == 0
    assert len(lines) <= len(mrc)
