"""Trace replay vs a dict-based oracle (the reference's pluss_access walk)."""

import numpy as np
import pytest

from pluss import mrc, trace
from pluss.config import NBINS


def oracle_replay(addrs, cls=64):
    """Literal re-enactment of pluss_access (pluss.cpp:126-160): line masking,
    global clock, last-access map; log2-binned reuse, cold key -1."""
    shift = int(cls).bit_length() - 1
    lat, hist, clock = {}, {}, 0
    for a in np.asarray(addrs).tolist():
        line = a >> shift
        if line in lat:
            r = clock - lat[line]
            key = 1 << (r.bit_length() - 1)
            hist[key] = hist.get(key, 0) + 1
        else:
            hist[-1] = hist.get(-1, 0) + 1
        lat[line] = clock
        clock += 1
    return hist


@pytest.mark.parametrize("seed,n", [(0, 1000), (1, 5000)])
def test_replay_matches_oracle(seed, n):
    rng = np.random.default_rng(seed)
    addrs = rng.integers(0, 1 << 20, n) * 8  # byte addresses, reuse-heavy
    res = trace.replay(addrs, window=1 << 10)  # force multiple windows
    assert res.total_count == n
    assert res.histogram() == oracle_replay(addrs)


def test_replay_single_window():
    addrs = np.array([0, 64, 0, 128, 64, 0], np.int64)
    res = trace.replay(addrs)
    # 0: cold, 64: cold, 0: reuse 2, 128: cold, 64: reuse 3->bin2, 0: reuse 3
    assert res.histogram() == {-1: 3.0, 2: 3.0}
    assert res.n_lines == 3


def test_replay_precompacted_ids():
    ids = np.array([0, 1, 0, 2, 1], np.int64)
    res = trace.replay(ids, precompacted=True)
    assert res.histogram() == oracle_replay(ids * 64)


def test_replay_feeds_mrc():
    rng = np.random.default_rng(7)
    addrs = rng.integers(0, 1 << 14, 20000) * 64
    res = trace.replay(addrs)
    curve = mrc.aet_mrc(res.histogram())
    assert curve[0] == 1.0
    assert (np.diff(curve) <= 1e-12).all()


def test_replay_empty_and_bad():
    assert trace.replay(np.array([], np.int64)).total_count == 0
    with pytest.raises(ValueError, match="1-D"):
        trace.replay(np.zeros((2, 2)))
    with pytest.raises(ValueError, match="power of two"):
        trace.lines_of(np.array([0]), cls=48)


def test_load_trace_roundtrip(tmp_path):
    addrs = np.array([8, 16, 8, 4096], np.uint64)
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    assert (trace.load_trace(str(p)) == addrs.astype(np.int64)).all()
    pt = tmp_path / "t.txt"
    pt.write_text("8\n0x10\n8\n4096\n")
    assert (trace.load_trace(str(pt), "text") == addrs.astype(np.int64)).all()


def test_replay_sparse_addresses_use_compaction():
    # line range >> 2^24 forces the vocabulary pass; histogram must still
    # match the oracle and n_lines the true unique count
    rng = np.random.default_rng(3)
    base = rng.integers(0, 1 << 44, 50, dtype=np.int64) * 64
    addrs = base[rng.integers(0, 50, 4000)]
    res = trace.replay(addrs, window=1 << 10)
    # cluster compaction allocates slack slots: table size >= touched lines
    assert res.n_lines >= len(np.unique(base // 64))
    assert res.histogram() == oracle_replay(addrs)


def test_replay_dense_range_shortcut_offsets():
    # lines in a small range far from zero: ids are range offsets
    addrs = (np.array([5, 6, 5, 7, 6], np.int64) + (1 << 30)) * 64
    res = trace.replay(addrs)
    assert res.n_lines == 3
    assert res.histogram() == oracle_replay(addrs)


@pytest.mark.parametrize("n_dev,n", [(8, 6000), (2, 4097)])
def test_shard_replay_matches_replay(n_dev, n):
    # sharded trace replay: per-device segment scans + tail exchange must be
    # bit-identical to the sequential replay, incl. cross-segment reuses
    # (hot lines recur everywhere) and the padded last segment
    from pluss.parallel.shard import default_mesh

    rng = np.random.default_rng(17)
    addrs = rng.integers(0, 1 << 12, n) * 64  # hot: reuses cross segments
    a = trace.replay(addrs, window=1 << 9)
    b = trace.shard_replay(addrs, mesh=default_mesh(n_dev), window=1 << 9)
    assert b.total_count == n
    assert a.histogram() == b.histogram()


def test_shard_replay_sparse_clusters():
    from pluss.parallel.shard import default_mesh

    rng = np.random.default_rng(23)
    base = rng.integers(0, 1 << 44, 30, dtype=np.int64) * 64
    addrs = base[rng.integers(0, 30, 5000)]
    a = trace.replay(addrs, window=1 << 9)
    b = trace.shard_replay(addrs, mesh=default_mesh(4), window=1 << 9)
    assert a.histogram() == b.histogram()
    assert a.n_lines == b.n_lines


def test_shard_replay_single_device_falls_back():
    from pluss.parallel.shard import default_mesh

    addrs = np.array([0, 64, 0, 128, 64, 0], np.int64)
    b = trace.shard_replay(addrs, mesh=default_mesh(1))
    assert b.histogram() == {-1: 3.0, 2: 3.0}


def test_replay_file_streams_matching_in_memory(tmp_path):
    # sparse clusters + tiny window + tiny initial capacity: exercises the
    # batched disk reads, the incremental compactor across batches, AND the
    # geometric device-table growth (each growth retraces the jit)
    rng = np.random.default_rng(11)
    base = rng.integers(0, 1 << 40, 40, dtype=np.int64) * 64
    addrs = base[rng.integers(0, 40, 6000)]
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    res = trace.replay_file(str(p), window=1 << 9, initial_capacity=8)
    ref = trace.replay(addrs, window=1 << 9)
    assert res.total_count == ref.total_count == 6000
    assert res.histogram() == ref.histogram() == oracle_replay(addrs)


def test_replay_file_partial_final_batch(tmp_path):
    # length not a multiple of the batch: final batch is padded/masked
    addrs = np.arange(100, dtype=np.int64) * 64
    addrs = np.concatenate([addrs, addrs])  # every line reused once
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    res = trace.replay_file(str(p), window=64)
    assert res.histogram() == oracle_replay(addrs)


def test_replay_file_text_fallback(tmp_path):
    pt = tmp_path / "t.txt"
    pt.write_text("0\n64\n0\n")
    res = trace.replay_file(str(pt), fmt="text")
    assert res.histogram() == oracle_replay([0, 64, 0])
    with pytest.raises(ValueError, match="unknown trace format"):
        trace.replay_file(str(pt), fmt="bogus")


def test_replay_u16_packed_feed():
    # working set under 2^16 lines takes the u16 wire format (halves the
    # feed vs int32); histogram must be identical to the oracle
    rng = np.random.default_rng(5)
    addrs = rng.integers(0, 1 << 12, 3000) * 64
    res = trace.replay(addrs, window=1 << 9)
    assert res.n_lines <= 1 << 16
    assert res.histogram() == oracle_replay(addrs)


def test_pack_ids_format_selection():
    ids = np.arange(10, dtype=np.int32)
    assert trace._pack_ids(ids, 1 << 10).dtype == np.uint16
    assert trace._pack_ids(ids, 1 << 20).dtype == np.uint8      # [n,3]
    assert trace._pack_ids(ids, 1 << 20).shape == (10, 3)
    assert trace._pack_ids(ids, 1 << 25).dtype == np.int32


def test_replay_file_u16_to_u24_growth(tmp_path):
    # the table crosses 2^16 mid-stream: early batches ship u16, later ones
    # 24-bit packed; the accumulated histogram must not care
    n_hot, n = 200, 4096
    rng = np.random.default_rng(19)
    first = rng.integers(0, n_hot, n // 2, dtype=np.int64)
    # second half touches a wide range -> compactor grows past 2^16
    second = rng.integers(0, 1 << 18, n - n // 2, dtype=np.int64)
    addrs = np.concatenate([first, second]) * 64
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    res = trace.replay_file(str(p), window=1 << 9, initial_capacity=64)
    assert res.n_lines > 1 << 16
    assert res.histogram() == oracle_replay(addrs)


def test_pack_file_and_replay_resident(tmp_path):
    # pack once, stage to (virtual) device memory, replay resident: must be
    # bit-identical to the streamed replay, incl. a ragged final batch
    rng = np.random.default_rng(29)
    window = 1 << 9
    n = 8 * window * 3 - 101
    addrs = rng.integers(0, 1 << 12, n, dtype=np.int64) * 64
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    packed = str(tmp_path / "t.pack")
    meta = trace.pack_file(str(p), packed, window=window)
    assert meta["n"] == n and meta["fmt"] == "u24"
    stats = {}
    res = trace.replay_resident(packed, meta, window=window, stats=stats)
    ref = trace.replay(addrs, window=window)
    assert res.total_count == n == stats["refs"]
    np.testing.assert_array_equal(res.hist, ref.hist)
    assert stats["upload_bytes"] >= n * 3 and stats["replay_s"] > 0
    # clock0 shift is histogram-invariant (the tunnel-memo defeater)
    res2 = trace.replay_resident(packed, meta, window=window,
                                 clock0=8 * window * 3)
    np.testing.assert_array_equal(res2.hist, ref.hist)


def test_replay_resident_limit_refs(tmp_path):
    rng = np.random.default_rng(31)
    window = 1 << 9
    n = 8 * window * 2
    addrs = rng.integers(0, 1 << 11, n, dtype=np.int64) * 64
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    packed = str(tmp_path / "t.pack")
    meta = trace.pack_file(str(p), packed, window=window)
    lim = 8 * window  # one full batch
    res = trace.replay_resident(packed, meta, window=window, limit_refs=lim)
    ref = trace.replay(addrs[:lim], window=window)
    assert res.total_count == lim
    # same prefix, but resident ids come from the WHOLE trace's compaction;
    # with a dense-range table the ids agree, so histograms match exactly
    np.testing.assert_array_equal(res.hist, ref.hist)


def test_shard_replay_file_matches_replay_file(tmp_path):
    """Disk-streamed sharded replay == single-device streamed replay, on a
    trace LARGER than any single slice buffer (VERDICT r2 task 5): 8
    segments x 4 windows each, streamed 2 windows per call."""
    import numpy as np

    from pluss import trace

    rng = np.random.default_rng(11)
    window = 1 << 10
    n = 8 * 4 * window - 137          # ragged tail exercises the padding
    addrs = (rng.integers(0, 1 << 13, n, dtype=np.int64) << 6).astype("<u8")
    p = tmp_path / "t.bin"
    addrs.tofile(p)
    a = trace.replay_file(str(p), window=window)
    b = trace.shard_replay_file(str(p), window=window, batch_windows=2,
                                initial_capacity=1 << 8)
    assert a.total_count == b.total_count == n
    np.testing.assert_array_equal(a.hist, b.hist)


def test_shard_replay_file_single_call(tmp_path):
    import numpy as np

    from pluss import trace

    rng = np.random.default_rng(12)
    window = 1 << 9
    n = 3 * window + 41
    addrs = (rng.integers(0, 1 << 10, n, dtype=np.int64) << 6).astype("<u8")
    p = tmp_path / "t.bin"
    addrs.tofile(p)
    a = trace.replay(np.asarray(np.frombuffer(addrs.tobytes(), "<u8"),
                                np.int64), window=window)
    b = trace.shard_replay_file(str(p), window=window)
    np.testing.assert_array_equal(a.hist, b.hist)


def test_shard_replay_file_ragged_slice_boundary(tmp_path):
    """S not divisible by batch_windows: the final slice of each segment
    must clip at the segment end instead of spilling into (and double
    counting with) the next device's segment — code-review r3 finding."""
    import numpy as np

    from pluss import trace

    rng = np.random.default_rng(13)
    window = 1 << 8
    n = 8 * 3 * window  # S=3 windows/segment; batch_windows=2 -> ragged
    addrs = (rng.integers(0, 1 << 11, n, dtype=np.int64) << 6).astype("<u8")
    p = tmp_path / "t.bin"
    addrs.tofile(p)
    a = trace.replay_file(str(p), window=window)
    b = trace.shard_replay_file(str(p), window=window, batch_windows=2)
    assert int(a.hist.sum()) == n
    np.testing.assert_array_equal(a.hist, b.hist)


def test_replay_file_deadline_truncates_cleanly(tmp_path):
    # a zero deadline stops after the first sync point; the result must be
    # an EXACT prefix replay with an honest total_count
    rng = np.random.default_rng(37)
    window = 1 << 8
    n = 8 * window * 12
    addrs = rng.integers(0, 1 << 11, n, dtype=np.int64) * 64
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    res = trace.replay_file(str(p), window=window, deadline_s=0.0)
    assert 0 < res.total_count < n
    assert res.total_count % (8 * window) == 0   # batch-boundary cut
    ref = trace.replay(addrs[:res.total_count], window=window)
    np.testing.assert_array_equal(res.hist, ref.hist)
    # no deadline: unchanged behavior
    full = trace.replay_file(str(p), window=window)
    assert full.total_count == n


def test_pack_file_i32_fallback_past_2pow24_lines(tmp_path):
    """Line tables past 2^24 ids restart the pack in the int32 wire
    format (PR-2 follow-up: the u24 path used to raise).  The compactor's
    slack makes the boundary cheap to cross: clusters spaced beyond the
    slack each reserve 1024 id slots, so ~16.5K refs overflow the table."""
    window = 1 << 9
    n_clusters = (1 << 24) // 1024 + 64
    lines = np.arange(n_clusters, dtype=np.int64) * 4096
    addrs = lines * 64
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    packed = str(tmp_path / "t.pack")
    meta = trace.pack_file(str(p), packed, window=window)
    assert meta["fmt"] == "i32"
    assert meta["n_lines"] >= 1 << 24
    assert meta["n"] == n_clusters
    import os

    assert os.path.getsize(packed) >= n_clusters * 4  # 4-byte wire records
    res = trace.replay_resident(packed, meta, window=window)
    assert res.total_count == n_clusters
    # all-distinct lines: pure cold misses — and bit-identical to the
    # streamed replay of the raw trace
    ref = trace.replay_file(str(p), window=window)
    np.testing.assert_array_equal(res.hist, ref.hist)
    assert int(res.hist[0]) == n_clusters


def test_pack_file_u24_boundary_stays_narrow(tmp_path):
    """A table just UNDER 2^24 ids keeps the 3-byte format."""
    window = 1 << 9
    n_clusters = 1000            # 1000 * 1024 slots < 2^24
    lines = np.arange(n_clusters, dtype=np.int64) * 4096
    p = tmp_path / "t.bin"
    (lines * 64).astype("<u8").tofile(p)
    packed = str(tmp_path / "t.pack")
    meta = trace.pack_file(str(p), packed, window=window)
    assert meta["fmt"] == "u24" and meta["n_lines"] < 1 << 24


def test_segmented_vs_legacy_scan_bit_identical():
    """The whole-batch segmented kernel (round-6 default) must reproduce
    the legacy per-window scan bit-for-bit — reuse gaps are partition-
    invariant and both histogram paths are integer-exact."""
    rng = np.random.default_rng(41)
    addrs = rng.integers(0, 1 << 13, 9000) * 64
    seg = trace.replay(addrs, window=1 << 9, segmented=True)
    leg = trace.replay(addrs, window=1 << 9, segmented=False)
    np.testing.assert_array_equal(seg.hist, leg.hist)
    assert seg.histogram() == oracle_replay(addrs)


def test_batch_windows_histogram_invariance(tmp_path):
    """The histogram must not depend on how the stream is cut into
    batches: batch_windows 1, 3 and the default all agree (and with the
    legacy scan at a non-default width)."""
    rng = np.random.default_rng(43)
    addrs = rng.integers(0, 1 << 11, 7000, dtype=np.int64) * 64
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    # segmented pinned on: the CPU backend's default is the legacy scan
    ref = trace.replay_file(str(p), window=1 << 9, segmented=True)
    for bw in (1, 3):
        res = trace.replay_file(str(p), window=1 << 9, batch_windows=bw,
                                segmented=True)
        np.testing.assert_array_equal(res.hist, ref.hist)
    leg = trace.replay_file(str(p), window=1 << 9, batch_windows=3,
                            segmented=False)
    np.testing.assert_array_equal(leg.hist, ref.hist)


@pytest.mark.parametrize("bw,qd", [(2, 1), (5, 4)])
def test_deadline_truncates_on_custom_batch_boundary(tmp_path, bw, qd):
    """deadline_s truncation must land exactly on a batch boundary under
    the overlapped (double-buffered) staging, for any --batch-windows and
    reader queue depth (ISSUE 4 satellite regression)."""
    rng = np.random.default_rng(47)
    window = 1 << 8
    n = bw * window * 9 + 17
    addrs = rng.integers(0, 1 << 11, n, dtype=np.int64) * 64
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    res = trace.replay_file(str(p), window=window, batch_windows=bw,
                            queue_depth=qd, deadline_s=0.0)
    assert 0 < res.total_count < n
    assert res.total_count % (bw * window) == 0   # exact batch boundary
    ref = trace.replay(addrs[:res.total_count], window=window)
    np.testing.assert_array_equal(res.hist, ref.hist)


def test_threaded_queue_depth_env(tmp_path, monkeypatch):
    """PLUSS_TRACE_QUEUE_DEPTH steers the reader queue bound (kwarg wins
    over env; both replay correctly)."""
    rng = np.random.default_rng(53)
    addrs = rng.integers(0, 1 << 10, 4000, dtype=np.int64) * 64
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    monkeypatch.setenv("PLUSS_TRACE_QUEUE_DEPTH", "1")
    a = trace.replay_file(str(p), window=1 << 9, batch_windows=2)
    b = trace.replay_file(str(p), window=1 << 9, batch_windows=2,
                          queue_depth=6)
    assert a.histogram() == b.histogram() == oracle_replay(addrs)


def test_ckpt_saves_live_prefix_only(tmp_path):
    """The replay checkpoint stores only the live last_pos prefix (plus
    the capacity), not the whole padded table — and a resume from it is
    bit-identical (ISSUE 4 satellite)."""
    from pluss.resilience import faults
    from pluss.resilience.errors import DataLoss

    rng = np.random.default_rng(59)
    window = 1 << 8
    bw = 2
    n = bw * window * 8
    addrs = rng.integers(0, 1 << 9, n, dtype=np.int64) * 64
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    ckpt = str(tmp_path / "t.ckpt.npz")
    ref = trace.replay_file(str(p), window=window, batch_windows=bw)

    faults.install(faults.FaultPlan.parse("trace_loss@5"))
    try:
        with pytest.raises(DataLoss):
            trace.replay_file(str(p), window=window, batch_windows=bw,
                              initial_capacity=1 << 12,
                              checkpoint_path=ckpt, checkpoint_every=1)
    finally:
        faults.install(None)
    with np.load(ckpt) as z:
        cap = int(z["capacity"])
        live = z["last_pos"].shape[0]
        assert cap == 1 << 12
        assert live < cap                  # only the prefix is on disk
        assert live >= (1 << 9)            # ...but all live slots are
    res = trace.replay_file(str(p), window=window, batch_windows=bw,
                            initial_capacity=1 << 12,
                            checkpoint_path=ckpt, resume=True)
    np.testing.assert_array_equal(res.hist, ref.hist)


def test_ckpt_rejects_different_batch_windows(tmp_path):
    """batch_windows is part of the checkpoint identity: a checkpoint cut
    at one batch width must never splice into a run at another."""
    from pluss.resilience import faults
    from pluss.resilience.errors import DataLoss

    rng = np.random.default_rng(61)
    window = 1 << 8
    n = 4 * window * 8
    addrs = rng.integers(0, 1 << 9, n, dtype=np.int64) * 64
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    ckpt = str(tmp_path / "t.ckpt.npz")
    faults.install(faults.FaultPlan.parse("trace_loss@5"))
    try:
        with pytest.raises(DataLoss):
            trace.replay_file(str(p), window=window, batch_windows=2,
                              checkpoint_path=ckpt, checkpoint_every=1)
    finally:
        faults.install(None)
    # resume at a DIFFERENT batch width: starts fresh, still exact
    res = trace.replay_file(str(p), window=window, batch_windows=4,
                            checkpoint_path=ckpt, resume=True)
    ref = trace.replay(addrs, window=window)
    np.testing.assert_array_equal(res.hist, ref.hist)


def test_batching_knobs_validated(tmp_path):
    """Invalid batch_windows / queue_depth must fail loudly: a negative
    batch count used to return an all-zero histogram claiming full
    coverage, and queue depth 0 makes python's Queue UNBOUNDED
    (code-review findings on the round-6 knobs)."""
    addrs = np.arange(100, dtype=np.int64) * 64
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    with pytest.raises(ValueError, match="batch_windows"):
        trace.replay(addrs, window=64, batch_windows=-4)
    with pytest.raises(ValueError, match="batch_windows"):
        trace.replay_file(str(p), window=64, batch_windows=0)
    with pytest.raises(ValueError, match="batch_windows"):
        trace.pack_file(str(p), str(tmp_path / "t.pack"), window=64,
                        batch_windows=-1)
    with pytest.raises(ValueError, match="queue_depth"):
        trace.replay_file(str(p), window=64, queue_depth=0)


def test_pack24_pack_unpack_roundtrip():
    """The vectorized _pack24 matches the 3-masked-stores reference
    byte-for-byte, including the 2^24-1 ceiling."""
    rng = np.random.default_rng(67)
    ids = np.concatenate([
        rng.integers(0, 1 << 24, 1000, dtype=np.int32),
        np.array([0, 1, 0xFF, 0x100, 0xFFFF, 0x10000, (1 << 24) - 1],
                 np.int32)])
    ref = np.empty((len(ids), 3), np.uint8)
    ref[:, 0] = ids & 0xFF
    ref[:, 1] = (ids >> 8) & 0xFF
    ref[:, 2] = (ids >> 16) & 0xFF
    out = trace._pack24(ids)
    assert out.flags["C_CONTIGUOUS"]
    np.testing.assert_array_equal(out, ref)
    # non-contiguous input (a strided slice) must pack identically
    np.testing.assert_array_equal(trace._pack24(ids[::2]), ref[::2])


def test_trace_smoke_wrapper():
    """The run.sh tier-1 smoke, importable: pack → replay_file →
    interrupted --resume → legacy A/B on a small synthetic trace."""
    from pluss import trace_smoke

    assert trace_smoke.main(n_refs=1 << 18, window=1 << 12,
                            batch_windows=4) == 0


def test_shard_replay_file_resume_checkpoint(tmp_path):
    """Interrupted sharded replay resumes from the journal + npz
    checkpoint bit-identically (PR-2 follow-up)."""
    import os

    rng = np.random.default_rng(17)
    window = 1 << 8
    n = 8 * 6 * window              # S=6 windows/segment on the 8-dev mesh
    addrs = (rng.integers(0, 1 << 11, n, dtype=np.int64) << 6)
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    ckpt = str(tmp_path / "shard.ckpt")
    ref = trace.replay_file(str(p), window=window)

    # run once WITH checkpointing every call, interrupting mid-run by
    # faulting a batch read of the final step call (n_calls = 3, D = 8:
    # hit 18 lands in call k=2, after the k_next=2 checkpoint)
    from pluss.resilience import faults
    from pluss.resilience.errors import DataLoss

    faults.install(faults.FaultPlan.parse("trace_loss@18"))
    try:
        with pytest.raises(DataLoss):
            trace.shard_replay_file(str(p), window=window,
                                    batch_windows=2, checkpoint_path=ckpt,
                                    checkpoint_every=1)
    finally:
        faults.install(None)
    assert os.path.exists(ckpt) and os.path.exists(ckpt + ".npz")

    # resume completes and matches the uninterrupted replay exactly
    res = trace.shard_replay_file(str(p), window=window, batch_windows=2,
                                  checkpoint_path=ckpt, resume=True)
    np.testing.assert_array_equal(res.hist, ref.hist)
    # a finished run retires its checkpoint
    assert not os.path.exists(ckpt) and not os.path.exists(ckpt + ".npz")


def test_shard_replay_file_resume_rejects_other_run(tmp_path):
    """A checkpoint for a DIFFERENT trace/shape starts fresh, never
    splices."""
    rng = np.random.default_rng(19)
    window = 1 << 8
    n = 8 * 4 * window
    addrs = (rng.integers(0, 1 << 10, n, dtype=np.int64) << 6)
    p = tmp_path / "t.bin"
    addrs.astype("<u8").tofile(p)
    ckpt = str(tmp_path / "shard.ckpt")
    # checkpoint from a different run identity (different window)
    from pluss.resilience.journal import Journal

    Journal(ckpt).record({"shard_ckpt": 1}, k_next=1, comp={},
                         n=n, window=window * 2, cls=64,
                         precompacted=False, D=8, SB=2, fp="deadbeef")
    np.savez(ckpt + ".npz", k_next=np.int64(1), capacity=np.int64(16),
             last_pos=np.zeros((8, 16)), hist=np.zeros((8, NBINS)),
             head_pos=np.zeros((8, 16)))
    res = trace.shard_replay_file(str(p), window=window, batch_windows=2,
                                  checkpoint_path=ckpt, resume=True)
    ref = trace.replay_file(str(p), window=window)
    np.testing.assert_array_equal(res.hist, ref.hist)
    # the foreign run's checkpoint must SURVIVE this run's retirement —
    # its owner may still want to resume (code-review finding)
    import os

    assert os.path.exists(ckpt) and os.path.exists(ckpt + ".npz")
