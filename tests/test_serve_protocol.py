"""Serving protocol: request parsing, the admission gate, spec codec,
response shaping, batch keys, and the SLO latency reservoir."""

import pytest

import tests.conftest  # noqa: F401  (CPU platform + x64)
from pluss.config import SHARE_CAP, SamplerConfig
from pluss.models import REGISTRY
from pluss.obs import LatencyReservoir
from pluss.resilience.errors import (
    DeadlineExceeded,
    InvalidRequest,
    Overloaded,
)
from pluss.serve.protocol import (
    error_response,
    parse_request,
    result_payload,
    spec_from_json,
    spec_to_json,
)


# ---------------------------------------------------------------------------
# inline spec codec


@pytest.mark.parametrize("model,n", [
    ("gemm", 16), ("mvt", 12), ("syrk_tri", 8), ("cholesky", 8),
    ("trmm", 8), ("fdtd2d", 8),
])
def test_spec_json_round_trip(model, n):
    """Encode → decode is the identity across the structural variety of
    the registry (rectangular, triangular, quad-contract, varying-start,
    multi-nest)."""
    spec = REGISTRY[model](n)
    assert spec_from_json(spec_to_json(spec)) == spec


@pytest.mark.parametrize("mutate,what", [
    (lambda d: d.pop("name"), "missing name"),
    (lambda d: d.update(arrays=[["A", 0]]), "zero-element array"),
    (lambda d: d.update(arrays="A"), "arrays not a list"),
    (lambda d: d.update(nests=[]), "empty nests"),
    (lambda d: d["nests"][0].pop("trip"), "loop without trip"),
    (lambda d: d["nests"][0].update(body=[]), "empty body"),
    (lambda d: d["nests"][0].update(trip="x"), "non-integer trip"),
    (lambda d: d["nests"][0]["body"].append({"x": 1}),
     "item neither loop nor ref"),
])
def test_spec_json_malformed(mutate, what):
    doc = spec_to_json(REGISTRY["gemm"](8))
    mutate(doc)
    with pytest.raises(InvalidRequest):
        spec_from_json(doc)


def test_spec_json_ref_field_validation():
    doc = spec_to_json(REGISTRY["gemm"](8))
    # walk to the first ref and corrupt its addr_terms
    loop = doc["nests"][0]
    while "body" in loop and "body" in loop["body"][0]:
        loop = loop["body"][0]
    ref = next(b for b in loop["body"] if "array" in b)
    ref["addr_terms"] = [[0, "x"]]
    with pytest.raises(InvalidRequest):
        spec_from_json(doc)


# ---------------------------------------------------------------------------
# parse_request / admission


def test_parse_model_request_defaults():
    r = parse_request({"model": "gemm", "n": 16})
    assert r.kind == "spec" and r.spec.name == "gemm16"
    assert r.cfg == SamplerConfig()
    assert r.share_cap == SHARE_CAP and r.window is None
    assert r.output == "mrc" and r.deadline is None
    assert not r.expired()


def test_parse_request_schedule_knobs():
    r = parse_request({"model": "mvt", "n": 12, "threads": 2, "chunk": 3,
                       "ds": 4, "cls": 32, "output": "both",
                       "share_cap": 64, "window": 4096})
    assert r.cfg == SamplerConfig(thread_num=2, chunk_size=3, ds=4, cls=32)
    assert (r.share_cap, r.window, r.output) == (64, 4096, "both")


def test_parse_inline_spec_request():
    doc = spec_to_json(REGISTRY["gemm"](13))
    doc["name"] = "tenant_custom"
    r = parse_request({"spec": doc, "threads": 2})
    assert r.kind == "spec" and r.spec.name == "tenant_custom"


def test_parse_request_id_echo_and_anon():
    assert parse_request({"id": 7, "model": "gemm", "n": 8}).id == "7"
    anon = parse_request({"model": "gemm", "n": 8}).id
    assert anon.startswith("anon-")


@pytest.mark.parametrize("obj,why", [
    ([], "not an object"),
    ({}, "no selector"),
    ({"model": "gemm", "trace": "/x"}, "two selectors"),
    ({"model": "no_such_model"}, "unknown model"),
    ({"model": "gemm", "n": -4}, "bad n rejected by the builder"),
    ({"model": "gemm", "threads": 0}, "bad threads"),
    ({"model": "gemm", "output": "csv"}, "bad output"),
    ({"model": "gemm", "deadline_ms": -1}, "bad deadline"),
    ({"model": "gemm", "deadline_ms": True}, "bool deadline"),
    ({"trace": "/no/such/file.bin"}, "missing trace file"),
    ({"trace": "/tmp", "fmt": "yaml"}, "bad trace fmt"),
    ({"sleep_ms": 10_000_000}, "sleep beyond the cap"),
])
def test_parse_request_rejections(obj, why):
    with pytest.raises(InvalidRequest):
        parse_request(obj)


def test_parse_request_deadline_stamped():
    r = parse_request({"model": "gemm", "n": 8, "deadline_ms": 10_000})
    rem = r.remaining_s()
    assert rem is not None and 8.0 < rem <= 10.0
    r2 = parse_request({"model": "gemm", "n": 8},
                       default_deadline_ms=5_000)
    assert 4.0 < r2.remaining_s() <= 5.0


def test_admission_gate_rejects_analyzer_errors():
    """A spec the PR-1 analyzer flags with ERROR diagnostics is refused
    at admission, with the findings attached as data."""
    # an out-of-bounds read: 1 array element, refs walk 8 — the bounds
    # prover rejects this class (the fdtd2d bug's shape)
    bad = {
        "name": "oob", "arrays": [["A", 1]],
        "nests": [{"trip": 8, "body": [
            {"name": "A1", "array": "A", "addr_terms": [[0, 1]]}]}],
    }
    with pytest.raises(InvalidRequest) as ei:
        parse_request({"spec": bad, "threads": 2})
    assert ei.value.diagnostics, "analyzer findings must be attached"
    assert all(d["severity"] == "ERROR" for d in ei.value.diagnostics)


def test_admission_size_bound(monkeypatch):
    monkeypatch.setenv("PLUSS_SERVE_MAX_REFS", "1000")
    with pytest.raises(InvalidRequest) as ei:
        parse_request({"model": "gemm", "n": 16})   # 16^3 * 3 refs > 1000
    assert "PLUSS_SERVE_MAX_REFS" in str(ei.value)
    parse_request({"model": "gemm", "n": 4})        # under the bound: fine


def test_parse_sleep_request():
    r = parse_request({"sleep_ms": 25})
    assert r.kind == "sleep" and r.sleep_ms == 25
    # sleep keys never coalesce
    r2 = parse_request({"sleep_ms": 25})
    assert r.batch_key() != r2.batch_key()


# ---------------------------------------------------------------------------
# batch keys


def test_batch_key_coalesces_equal_plans():
    a = parse_request({"model": "gemm", "n": 16, "threads": 2})
    b = parse_request({"model": "gemm", "n": 16, "threads": 2,
                       "output": "histogram", "deadline_ms": 50,
                       "id": "zzz"})
    assert a.batch_key() == b.batch_key(), \
        "output/deadline/id are demux concerns, not dispatch concerns"


@pytest.mark.parametrize("delta", [
    {"n": 12}, {"threads": 4}, {"chunk": 2}, {"cls": 32},
    {"window": 4096}, {"share_cap": 64}, {"model": "mvt", "n": 16},
])
def test_batch_key_separates_different_plans(delta):
    base = {"model": "gemm", "n": 16, "threads": 2}
    assert parse_request(base).batch_key() != \
        parse_request({**base, **delta}).batch_key()


def test_batch_key_ignores_cache_kb():
    """cache_kb only steers the post-dispatch AET/MRC conversion: two
    requests differing in cache size alone must SHARE the dispatch and
    diverge at demux (result_payload shapes with each request's cfg)."""
    a = parse_request({"model": "gemm", "n": 16, "cache_kb": 2560})
    b = parse_request({"model": "gemm", "n": 16, "cache_kb": 512})
    assert a.batch_key() == b.batch_key()
    assert a.cfg.cache_kb != b.cfg.cache_kb


def test_batch_key_trace_requests(tmp_path):
    import numpy as np

    p1, p2 = tmp_path / "a.bin", tmp_path / "b.bin"
    for p in (p1, p2):
        np.arange(64, dtype="<u8").tofile(p)
    a = parse_request({"trace": str(p1)})
    b = parse_request({"trace": str(p1), "output": "both"})
    c = parse_request({"trace": str(p2)})
    assert a.batch_key() == b.batch_key() != c.batch_key()


# ---------------------------------------------------------------------------
# responses


def test_error_response_taxonomy_bits():
    doc = error_response("r1", Overloaded("full", site="serve.admission"))
    assert doc == {"id": "r1", "ok": False, "error": {
        "type": "Overloaded", "message": "[serve.admission] full",
        "retryable": True, "degradable": False}}
    doc = error_response(None, DeadlineExceeded("late"))
    assert doc["error"]["type"] == "DeadlineExceeded"
    assert not doc["error"]["retryable"]
    # non-Pluss errors are wrapped, never raw
    doc = error_response("x", RuntimeError("boom"))
    assert doc["error"]["type"] == "InternalError"
    diag = InvalidRequest("bad", diagnostics=({"code": "PL201"},))
    assert error_response("y", diag)["error"]["diagnostics"] == \
        [{"code": "PL201"}]


def test_result_payload_output_shaping():
    ri = {-1: 3.0, 4: 10.0, 64: 2.0}
    cfg = SamplerConfig()
    req = parse_request({"model": "gemm", "n": 8, "output": "mrc"})
    p = result_payload(req, ri, cfg)
    assert "mrc" in p and "histogram" not in p
    req.output = "histogram"
    p = result_payload(req, ri, cfg)
    assert p["histogram"] == {"-1": 3.0, "4": 10.0, "64": 2.0}
    req.output = "both"
    p = result_payload(req, ri, cfg)
    assert set(p) == {"mrc", "histogram"}
    # the mrc matches the direct pipeline
    from pluss import mrc as mrc_mod

    expect = [[int(c), float(m)]
              for c, m in mrc_mod.dedup_lines(mrc_mod.aet_mrc(ri, cfg))]
    assert p["mrc"] == expect


# ---------------------------------------------------------------------------
# SLO reservoir


def test_latency_reservoir_quantiles():
    r = LatencyReservoir(capacity=100)
    assert r.quantile(0.5) is None
    for v in range(1, 101):
        r.add(float(v))
    assert r.count == 100
    assert r.quantile(0.0) == 1.0
    assert r.quantile(1.0) == 100.0
    assert 49.0 <= r.quantile(0.5) <= 52.0
    assert 97.0 <= r.quantile(0.99) <= 100.0


def test_latency_reservoir_slides():
    r = LatencyReservoir(capacity=10)
    for v in range(1000):
        r.add(float(v))
    # only the last 10 samples remain
    assert r.quantile(0.0) >= 990.0
    assert r.count == 1000


def test_latency_reservoir_validation():
    with pytest.raises(ValueError):
        LatencyReservoir(capacity=0)
    r = LatencyReservoir()
    with pytest.raises(ValueError):
        r.quantile(1.5)
