"""Spec analyzer tests: registry-wide cleanliness, adversarial specs per
diagnostic code, and the race detector's carried-level classification
cross-checked against the ENGINE's dynamic share split.

This file is the fast tier-1 gate the driver relies on: a broken spec in
``pluss.models.REGISTRY`` fails here (pure host analysis, ~1 s for the
whole registry) before any engine run gets a chance to enumerate it.
"""

from __future__ import annotations

import json

import pytest

from pluss import analysis, cli, engine
from pluss.analysis import Severity, deps
from pluss.config import SamplerConfig
from pluss.models import REGISTRY, gemm
from pluss.models.polybench import syrk_triangular
from pluss.spec import Loop, LoopNestSpec, Ref, share_span_formula
from tests.oracle import OracleSampler


# ---------------------------------------------------------------------------
# registry-wide: every family proves clean (no ERROR diagnostics)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(REGISTRY))
def test_registry_family_lints_clean(name):
    spec = REGISTRY[name]()  # the default size run.sh / bench actually use
    diags = analysis.lint_spec(spec)
    errors = [d.format() for d in diags if d.severity is Severity.ERROR]
    assert not errors, f"{name}: {errors}"


def test_registry_writes_declared():
    # is_write threading sanity: every family declares at least one store
    # (each models a kernel with an output), and never ALL-stores
    for name in sorted(REGISTRY):
        from pluss.analysis.walk import ref_sites

        sites = ref_sites(REGISTRY[name](16))
        writes = [s for s in sites if s.ref.is_write]
        assert writes, f"{name} declares no store"
        assert len(writes) < len(sites), f"{name} declares only stores"


# ---------------------------------------------------------------------------
# adversarial specs: one expected code each
# ---------------------------------------------------------------------------

def _codes(spec, severity=None):
    return {d.code for d in analysis.lint_spec(spec)
            if severity is None or d.severity is severity}


def _nest(body, trip=8):
    return Loop(trip=trip, body=(Loop(trip=trip, body=body),))


def test_oob_ref_flags_pl101():
    spec = LoopNestSpec("oob", (("A", 8 * 8),), (_nest((
        # row walks to 8*8 + 7: one full row past the declared size
        Ref("A0", "A", addr_terms=((0, 8), (1, 1)), addr_base=8),
    ),),))
    assert "PL101" in _codes(spec, Severity.ERROR)


def test_negative_addr_flags_pl101():
    spec = LoopNestSpec("neg", (("A", 64),), (_nest((
        Ref("A0", "A", addr_terms=((0, 8), (1, 1)), addr_base=-1),
    ),),))
    assert "PL101" in _codes(spec, Severity.ERROR)


def test_undeclared_array_flags_pl102():
    spec = LoopNestSpec("ghost", (("A", 64),), (_nest((
        Ref("B0", "B", addr_terms=((0, 8), (1, 1))),
    ),),))
    assert "PL102" in _codes(spec, Severity.ERROR)


def test_unused_array_flags_pl103():
    spec = LoopNestSpec("dead", (("A", 64), ("Z", 64)), (_nest((
        Ref("A0", "A", addr_terms=((0, 8), (1, 1))),
    ),),))
    assert "PL103" in _codes(spec, Severity.WARNING)


def test_wrong_share_span_flags_pl202():
    spec = LoopNestSpec("span", (("B", 64),), (_nest((
        # hand-copied constant: correct would be share_span_formula(8) = 73
        Ref("B0", "B", addr_terms=((1, 8),), share_span=999),
    ),),))
    assert "PL202" in _codes(spec)
    good = LoopNestSpec("span_ok", (("B", 64),), (_nest((
        Ref("B0", "B", addr_terms=((1, 8),),
            share_span=share_span_formula(8)),
    ),),))
    assert "PL202" not in _codes(good)


def test_degenerate_share_span_flags_pl201():
    spec = LoopNestSpec("span0", (("B", 64),), (_nest((
        Ref("B0", "B", addr_terms=((1, 8),), share_span=0),
    ),),))
    assert "PL201" in _codes(spec, Severity.ERROR)


def test_write_write_race_flags_pl301():
    # both stores hit B[j] with no parallel-iterator term: every parallel
    # iteration rewrites the same addresses
    spec = LoopNestSpec("ww", (("B", 8),), (_nest((
        Ref("B0", "B", addr_terms=((1, 1),), is_write=True),
        Ref("B1", "B", addr_terms=((1, 1),), is_write=True),
    ),),))
    assert "PL301" in _codes(spec, Severity.WARNING)


def test_read_write_race_flags_pl302():
    spec = LoopNestSpec("rw", (("B", 8),), (_nest((
        Ref("B0", "B", addr_terms=((1, 1),)),
        Ref("B1", "B", addr_terms=((1, 1),), is_write=True),
    ),),))
    codes = _codes(spec, Severity.WARNING)
    assert "PL302" in codes


def test_private_writes_raise_no_race():
    # store involves the parallel iterator: provably race-free (the GCD/
    # Banerjee test REFUTES the conflict, not just fails to confirm it)
    spec = LoopNestSpec("priv", (("B", 64),), (_nest((
        Ref("B0", "B", addr_terms=((0, 8), (1, 1))),
        Ref("B1", "B", addr_terms=((0, 8), (1, 1)), is_write=True),
    ),),))
    assert not {"PL301", "PL302"} & _codes(spec)


def test_bounded_parallel_loop_flags_pl401():
    spec = LoopNestSpec("p", (("A", 64),), (Loop(
        trip=8, bound_coef=(1, 1),
        body=(Ref("A0", "A", addr_terms=((0, 1),)),),
    ),))
    assert "PL401" in _codes(spec, Severity.ERROR)


def test_escaping_bound_flags_pl402():
    spec = LoopNestSpec("b", (("A", 64),), (Loop(trip=8, body=(
        Loop(trip=4, bound_coef=(1, 1),  # 1 + k reaches 8 > trip 4
             body=(Ref("A0", "A", addr_terms=((0, 8), (1, 1))),)),
    )),))
    assert "PL402" in _codes(spec, Severity.ERROR)


def test_addr_depth_flags_pl403():
    spec = LoopNestSpec("d", (("A", 64),), (Loop(trip=8, body=(
        Ref("A0", "A", addr_terms=((3, 1),)),
    )),))
    assert "PL403" in _codes(spec, Severity.ERROR)


def test_bad_bound_level_flags_pl404():
    spec = LoopNestSpec("bl", (("A", 64),), (Loop(trip=8, body=(
        Loop(trip=8, bound_coef=(0, 1), bound_level=3,
             body=(Ref("A0", "A", addr_terms=((0, 8), (1, 1))),)),
    )),))
    assert "PL404" in _codes(spec, Severity.ERROR)


def test_quad_contract_violation_flags_pl405():
    # bound-referenced level with start=1: index != value
    spec = LoopNestSpec("q", (("A", 64),), (Loop(trip=8, body=(
        Loop(trip=8, start=1, body=(
            Loop(trip=8, bound_coef=(0, 1), bound_level=1,
                 body=(Ref("A0", "A", addr_terms=((0, 8), (2, 1))),)),
        )),
    )),))
    assert "PL405" in _codes(spec, Severity.ERROR)


def test_duplicate_ref_names_do_not_shadow_diagnostics():
    # two refs named X0 in one nest: the FIRST carries a broken span.
    # Classification is keyed by tree path, so the duplicate name (a
    # PL406 warning) must not mask the first ref's PL201 ERROR.
    spec = LoopNestSpec("dup", (("B", 64),), (_nest((
        Ref("X0", "B", addr_terms=((1, 8),), share_span=0),
        Ref("X0", "B", addr_terms=((0, 8), (1, 1))),
    ),),))
    codes_err = _codes(spec, Severity.ERROR)
    assert "PL201" in codes_err
    assert "PL406" in _codes(spec, Severity.WARNING)


def test_contract_errors_gate_semantic_passes():
    # the PL401 nest would crash bounds/deps if they ran on it; the second
    # (valid) nest must still be analyzed
    spec = LoopNestSpec("gate", (("A", 8), ("B", 8)), (
        Loop(trip=8, bound_coef=(1, 1),
             body=(Ref("A0", "A", addr_terms=((0, 1),)),)),
        Loop(trip=8, body=(Ref("B0", "B", addr_terms=((0, 1),),
                               addr_base=4, is_write=True),)),
    ))
    codes = _codes(spec)
    assert "PL401" in codes         # nest 0 rejected
    assert "PL101" in codes         # nest 1 still bounds-checked


# ---------------------------------------------------------------------------
# diagnostics framework
# ---------------------------------------------------------------------------

def test_emitted_codes_are_registered():
    from pluss.analysis.diagnostics import CODES

    seen = set()
    for name in sorted(REGISTRY):
        seen |= {d.code for d in analysis.lint_spec(REGISTRY[name](16))}
    assert seen <= set(CODES)


def test_readme_code_table_matches_registry():
    import os
    import re

    from pluss.analysis.diagnostics import CODES

    readme = open(os.path.join(os.path.dirname(__file__), "..",
                               "README.md")).read()
    documented = set(re.findall(r"\bPL\d{3}\b", readme))
    assert documented == set(CODES), (
        "README diagnostic-code table out of sync with "
        "pluss.analysis.diagnostics.CODES")
    # every registered code must have an actual TABLE ROW with a valid
    # severity word — a prose mention alone doesn't document a code
    rows = dict(re.findall(r"^\| (PL\d{3}) \| (\w+) \|", readme,
                           flags=re.M))
    assert set(rows) == set(CODES), (
        "README is missing a code-table row for: "
        f"{sorted(set(CODES) - set(rows))}")
    assert set(rows.values()) <= {"error", "warning", "info"}
    # the r12 prediction family documents its emitted severities exactly
    assert rows["PL701"] == "warning"     # refusal, not a broken spec
    assert rows["PL702"] == "warning"
    assert rows["PL703"] == "info"
    assert rows["PL704"] == "error"       # prover soundness violation


def test_diagnostic_json_roundtrip():
    diags = analysis.lint_spec(REGISTRY["durbin"](16))
    doc = json.loads(analysis.format_json(diags))
    assert doc["errors"] == 0
    assert doc["warnings"] == sum(
        1 for d in diags if d.severity is Severity.WARNING)
    assert all(d["code"] in analysis.CODES for d in doc["diagnostics"])


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_lint_single_model(capsys):
    assert cli.main(["lint", "--model", "gemm", "--n", "16"]) == 0
    out = capsys.readouterr().out
    assert "0 error(s)" in out


def test_cli_lint_all(capsys):
    # the run.sh pre-pass: every registered family at its default size
    assert cli.main(["lint", "--all"]) == 0
    out = capsys.readouterr().out
    assert f"{len(REGISTRY)} model(s), 0 error(s)" in out


def test_cli_lint_json(capsys):
    assert cli.main(["lint", "--model", "syrk_tri", "--n", "16",
                     "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["errors"] == 0
    assert any(d["code"] == "PL303" for d in doc["diagnostics"])


def test_cli_verify_pre_pass(capsys):
    # opt-in --verify on an engine mode: clean spec runs normally
    assert cli.main(["acc", "--n", "8", "--backends", "seq",
                     "--verify"]) == 0
    assert "max iteration traversed" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# carried-level classification vs the engine's dynamic share split
# ---------------------------------------------------------------------------

class InstrumentedOracle(OracleSampler):
    """OracleSampler recording, per static reference, (a) whether it ever
    observes a reuse whose previous access came from a DIFFERENT parallel
    iteration (same thread — the oracle's LAT is per-thread), and (b)
    whether it ever observes a share-classified reuse.  The walk itself is
    unchanged (super()._access does the real accounting), so comparing the
    final histograms against engine.run ties these per-ref observations to
    the engine's own dynamic share split."""

    def __init__(self, spec, cfg):
        super().__init__(spec, cfg)
        self.cross_refs: set[str] = set()
        self.share_refs: set[str] = set()
        self._pv = [{name: {} for name, _ in spec.arrays}
                    for _ in range(cfg.thread_num)]

    def _access(self, tid, ref, ivs):
        addr = ref.addr_base + sum(c * ivs[d] for d, c in ref.addr_terms)
        line = addr * self.cfg.ds // self.cfg.cls
        lat = self.lat[tid][ref.array]
        if line in lat:
            reuse = self.count[tid] - lat[line]
            if self._pv[tid][ref.array][line] != ivs[0]:
                self.cross_refs.add(ref.name)
            if ref.share_span is not None and \
                    abs(reuse - 0) > abs(reuse - ref.share_span):
                self.share_refs.add(ref.name)
        self._pv[tid][ref.array][line] = ivs[0]
        super()._access(tid, ref, ivs)


def _crosscheck(spec, cfg):
    res = engine.run(spec, cfg)
    inst = InstrumentedOracle(spec, cfg).run()
    # (1) the engine's dynamic split IS the oracle's — so the per-ref
    # observations below speak for the engine, not just the oracle
    assert res.max_iteration_count == inst.max_iteration_count
    assert res.noshare_list() == inst.noshare
    assert res.share_list() == [
        {k: dict(v) for k, v in h.items()} for h in inst.share
    ]
    classes = deps.classify(spec)
    ana_cross = {rc.site.ref.name for rc in classes.values()
                 if rc.cross_observed}
    return res, inst, classes, ana_cross


@pytest.mark.parametrize("build", [gemm, syrk_triangular],
                         ids=["gemm", "syrk_tri"])
def test_carried_level_agrees_with_engine_share_split(build):
    # cls == ds: one element per cache line, so the element-granular race
    # analysis and the line-granular dynamic reuse accounting see the same
    # geometry (the fdtd2d engine test pins cls=8 for the same reason)
    spec = build(8)
    cfg = SamplerConfig(thread_num=2, chunk_size=2, cls=8)
    res, inst, classes, ana_cross = _crosscheck(spec, cfg)
    # (2) carried-level answers == dynamically observed cross-parallel
    # reuses, exactly, per static reference
    assert inst.cross_refs == ana_cross
    # (3) the spanned refs are exactly the classifier's cross-thread refs
    spanned = {rc.site.ref.name for rc in classes.values()
               if rc.site.ref.share_span is not None}
    assert spanned == ana_cross
    # (4) dynamic share events occur only at refs the detector classifies
    # as parallel-carried — and they DO occur (nonempty split)
    assert inst.share_refs <= ana_cross
    assert inst.share_refs, "expected a nonempty dynamic share split"
    assert any(h for h in res.share_list())
    # (5) the classifier's carried level for those refs is the parallel
    # loop (level 0)
    for rc in classes.values():
        if rc.site.ref.name in inst.share_refs:
            assert rc.carried_level == 0


@pytest.mark.parametrize("name", ["syrk", "trmm", "trisolv", "atax",
                                  "floyd_warshall", "conv2d",
                                  # multi-nest: cross-NEST reuse through
                                  # the persistent per-thread LAT must be
                                  # classified too
                                  "jacobi2d", "fdtd2d", "heat3d", "mvt"])
def test_dynamic_cross_reuse_is_subset_of_static(name):
    # soundness on a wider family sample: every dynamically observed
    # cross-parallel reuse must be statically classified as such (the
    # detector may over-approximate — Banerjee — but must never refute a
    # reuse that happens)
    spec = REGISTRY[name](8)
    cfg = SamplerConfig(thread_num=2, chunk_size=2, cls=8)
    inst = InstrumentedOracle(spec, cfg).run()
    classes = deps.classify(spec)
    ana_cross = {rc.site.ref.name for rc in classes.values()
                 if rc.cross_observed}
    assert inst.cross_refs <= ana_cross
