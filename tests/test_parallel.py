"""Sharded backend ≡ vmap engine ≡ oracle on a virtual 8-device CPU mesh.

SURVEY.md §4: the reference's real test oracle is "parallel semantics identical
to sequential enumeration"; here that property is asserted across a real
``shard_map`` boundary with psum merges, which the driver separately dry-runs
via ``__graft_entry__.dryrun_multichip``.
"""

import jax
import pytest

from pluss.config import SamplerConfig
from pluss.engine import run
from pluss.models import REGISTRY, gemm
from pluss.parallel import default_mesh, shard_run


def assert_same(a, b):
    assert a.max_iteration_count == b.max_iteration_count
    assert a.noshare_dense.tolist() == b.noshare_dense.tolist()
    assert a.share_raw == b.share_raw


@pytest.mark.parametrize("n_dev", [2, 8])
def test_shard_matches_vmap_gemm(n_dev):
    cfg = SamplerConfig(cls=8)  # 1 element/line: rich share activity
    spec = gemm(16)
    assert_same(shard_run(spec, cfg, mesh=default_mesh(n_dev)), run(spec, cfg))


def test_shard_matches_vmap_default_cfg():
    spec = gemm(16)
    cfg = SamplerConfig()
    assert_same(shard_run(spec, cfg, mesh=default_mesh(8)), run(spec, cfg))


def test_shard_odd_size_partial_chunks():
    cfg = SamplerConfig(cls=8)
    spec = gemm(13)
    assert_same(shard_run(spec, cfg, mesh=default_mesh(8)), run(spec, cfg))


def test_shard_multi_nest_cross_device_carry():
    # 2mm: lines live across nests, so cross-(nest, device) boundary
    # resolution is exercised in both directions
    cfg = SamplerConfig(cls=8)
    spec = REGISTRY["2mm"](8)
    assert_same(shard_run(spec, cfg, mesh=default_mesh(8)), run(spec, cfg))


def test_shard_more_devices_than_rounds():
    # gemm(8): 2 chunks/thread at CS=4 -> 1 round; 8 devices > rounds, so
    # most devices hold fully-invalid windows
    cfg = SamplerConfig(cls=8)
    spec = gemm(8)
    assert_same(shard_run(spec, cfg, mesh=default_mesh(8)), run(spec, cfg))


def test_mesh_is_virtual_8_cpu():
    assert len(jax.devices()) == 8


def test_shard_dynamic_assignment_and_resume():
    # dynamic chunk->thread map + setStartPoint resume through the sharded
    # backend must agree with the single-device engine
    from pluss.engine import run
    from pluss.parallel.shard import default_mesh, shard_run
    from pluss.sched import ChunkSchedule

    cfg = SamplerConfig(cls=8)
    spec = gemm(16)
    sched = ChunkSchedule(cfg.chunk_size, 16, 0, 1, cfg.thread_num)
    asg = tuple((c + 1) % cfg.thread_num for c in range(sched.n_chunks))
    for kw in ({"assignment": (asg,)}, {"start_point": 8}):
        a = run(spec, cfg, **kw)
        b = shard_run(spec, cfg, mesh=default_mesh(4), **kw)
        assert a.noshare_dense.tolist() == b.noshare_dense.tolist()
        assert a.share_list() == b.share_list()


def test_shard_ultra_template_path_matches_engine():
    # gemm(64): 16 chunks / 4 threads = 4 rounds -> a 4-device mesh gives one
    # FULL clean window per device, activating the static-template shard path
    from pluss.engine import plan, run
    from pluss.parallel.shard import default_mesh, shard_run

    cfg = SamplerConfig()
    pl = plan(gemm(64), cfg, n_windows=4)
    n = pl.nests[0]
    assert n.tpl is not None and n.clean.all(), "precondition: ultra active"
    a = run(gemm(64), cfg)
    b = shard_run(gemm(64), cfg, mesh=default_mesh(4))
    assert a.noshare_dense.tolist() == b.noshare_dense.tolist()
    assert a.share_list() == b.share_list()
    assert a.max_iteration_count == b.max_iteration_count


def test_shard_mixed_clean_windows_per_device_branch():
    # gemm(24) on 4 devices: rounds 0 (clean for all threads) and 1 (threads
    # 2,3 idle) land on different devices, so template and sort branches run
    # side by side in one SPMD program; results must match the engine
    from pluss.engine import plan, run
    from pluss.parallel.shard import default_mesh, shard_run

    cfg = SamplerConfig(cls=8)
    pl = plan(gemm(24), cfg, n_windows=4)
    n = pl.nests[0]
    mask = n.clean.all(axis=0)
    assert n.tpl is not None and mask.any() and not mask.all(), "precondition"
    a = run(gemm(24), cfg)
    b = shard_run(gemm(24), cfg, mesh=default_mesh(4))
    assert a.noshare_dense.tolist() == b.noshare_dense.tolist()
    assert a.share_list() == b.share_list()


def test_shard_subwindows_bounded_memory():
    # VERDICT r1 weak #3: per-device sort memory must be bounded by the
    # engine's window target, not the workload size.  A tiny window target
    # forces S > 1 sub-windows per device, so each device scans its share
    # of the stream instead of sorting it in one buffer; results must still
    # match the engine exactly (incl. heads carried across sub-windows).
    from pluss.engine import natural_n_windows
    from pluss.parallel.shard import _compiled

    cfg = SamplerConfig()
    spec = gemm(128)  # 32 chunks / 4 threads = 8 rounds
    wa = 1  # window target below one round -> one round per sub-window
    assert natural_n_windows(spec, cfg, window_accesses=wa) == 8
    a = run(spec, cfg, window_accesses=wa)
    b = shard_run(spec, cfg, mesh=default_mesh(4), window_accesses=wa)
    assert_same(a, b)
    pl, _ = _compiled(spec, cfg, 4096, default_mesh(4), window_accesses=wa)
    assert pl.nests[0].n_windows == 8  # 4 devices x S=2 sub-windows


def test_shard_subwindows_template_ineligible():
    # syrk is template-ineligible for its A refs by construction: with
    # forced sub-windows the sort path carries heads/tails across windows
    # inside each device (2-device mesh, 4 rounds -> S=2)
    spec = REGISTRY["syrk"](64)
    cfg = SamplerConfig()
    a = run(spec, cfg, window_accesses=1)
    b = shard_run(spec, cfg, mesh=default_mesh(2), window_accesses=1)
    assert_same(a, b)


def test_shard_subwindows_dynamic_assignment_and_resume():
    from pluss.sched import ChunkSchedule

    cfg = SamplerConfig(cls=8)
    spec = gemm(64)  # 4 rounds; 2-device mesh -> S=2
    sched = ChunkSchedule(cfg.chunk_size, 64, 0, 1, cfg.thread_num)
    asg = tuple((c + 1) % cfg.thread_num for c in range(sched.n_chunks))
    for kw in ({"assignment": (asg,)}, {"start_point": 24}):
        a = run(spec, cfg, window_accesses=1, **kw)
        b = shard_run(spec, cfg, mesh=default_mesh(2), window_accesses=1,
                      **kw)
        assert a.noshare_dense.tolist() == b.noshare_dense.tolist()
        assert a.share_list() == b.share_list()


def test_shard_subwindows_multi_nest():
    # 2mm at 4 rounds/nest on a 2-device mesh: cross-(nest, device,
    # sub-window) carries all at once
    spec = REGISTRY["2mm"](64)
    cfg = SamplerConfig()
    a = run(spec, cfg, window_accesses=1)
    b = shard_run(spec, cfg, mesh=default_mesh(2), window_accesses=1)
    assert_same(a, b)


def test_shard_var_refs_in_template_window():
    # syrk: A's two parallel-dim coefficients make it template-ineligible
    # (engine._split_ref_groups), so clean shard windows run the template for
    # C AND the var sort part for A; the dense boundary arrays of the two
    # merge by disjoint line ranges (shard._nest_results tpl_all)
    from pluss.engine import plan

    cfg = SamplerConfig()
    spec = REGISTRY["syrk"](64)
    pl = plan(spec, cfg, n_windows=4)
    n = pl.nests[0]
    assert n.tpl is not None and n.var_refs, "precondition: split groups"
    assert n.ultra_windows().any(), "precondition: template branch taken"
    assert_same(shard_run(spec, cfg, mesh=default_mesh(4)), run(spec, cfg))


def test_shard_share_cap_auto_retry_matches_engine():
    """The graceful share-cap auto-retry contract covers the sharded
    backend too (engine.run / run_sliced / shard_run all re-run at a
    covering cap instead of dying on default knobs)."""
    from pluss.engine import run
    from pluss.models import REGISTRY
    from pluss.parallel.shard import default_mesh, shard_run

    spec = REGISTRY["conv2d"](16)
    cfg = SamplerConfig(cls=8)
    want = run(spec, cfg)
    got = shard_run(spec, cfg, share_cap=1, mesh=default_mesh(4))
    assert got.max_iteration_count == want.max_iteration_count
    assert (got.noshare_dense == want.noshare_dense).all()
    assert got.share_list() == want.share_list()
