"""pluss.iteration: interleaving order, equality/dedup, hashing.

The scalar :func:`pluss.iteration.compare` is the executable spec
(iteration.rs:151-194 semantics); the vectorized key matrix must sort any
batch identically.
"""

from __future__ import annotations

import functools
import random

import numpy as np
import pytest

from pluss.iteration import (
    HASH_IV_BITS,
    IterationPoint,
    compare,
    dedup,
    interleaved_argsort,
    iv_bitmap,
    order_keys,
    point_hash,
)
from pluss.sched import ChunkSchedule


def _sched(trip=32, cs=4, T=4):
    return ChunkSchedule(cs, trip, 0, 1, T)


def _random_points(rng, n, trip, depth, n_refs=4):
    """Random fixed-depth points; priority = ref id (distinct per ref)."""
    pts = []
    for _ in range(n):
        ref = rng.randrange(n_refs)
        ivs = tuple(rng.randrange(trip) for _ in range(depth))
        pts.append(IterationPoint(f"R{ref}", ivs, priority=n_refs - ref))
    return pts


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_lexsort_matches_scalar_comparator(depth):
    rng = random.Random(20260730 + depth)
    sched = _sched()
    pts = _random_points(rng, 200, sched.trip, depth)
    ivs = np.array([p.ivs for p in pts])
    prios = np.array([p.priority for p in pts])
    idx = interleaved_argsort(ivs, prios, sched)
    got = [pts[i] for i in idx]
    want = sorted(pts, key=functools.cmp_to_key(
        lambda a, b: compare(a, b, sched)))
    key = lambda p: (p.ivs, p.priority)
    assert [key(p) for p in got] == [key(p) for p in want]


def test_comparator_orders_by_round_pos_then_tid():
    """Uniform interleaving: round-major, in-chunk pos, inner ivs, tid."""
    sched = _sched(trip=32, cs=4, T=4)
    c = IterationPoint("A", (1, 0))      # cid 0, tid 0, pos 1
    d = IterationPoint("A", (16, 0))     # cid 1, tid 0, pos 0
    # same (cid, pos): inner ivs decide before tid
    assert compare(IterationPoint("A", (0, 5)),
                   IterationPoint("A", (4, 0)), sched) == 1
    # inner ivs equal: tid decides
    a2 = IterationPoint("A", (0, 7))
    b2 = IterationPoint("A", (4, 7))
    assert compare(a2, b2, sched) == -1  # tid 0 < tid 1
    assert compare(b2, c, sched) == -1   # pos 0 < pos 1 beats tid/ivs
    assert compare(c, d, sched) == -1    # cid 0 < cid 1 dominates
    # priority: higher executes earlier
    hi = IterationPoint("A", (0, 7), priority=2)
    lo = IterationPoint("B", (0, 7), priority=1)
    assert compare(hi, lo, sched) == -1


def test_single_thread_order_is_program_order():
    """Points of one simulated thread sort into that thread's walk order."""
    sched = _sched(trip=8, cs=2, T=2)
    # nest: for i (parallel) / for j: R0[i,j]; R1[i,j]  (priority 2, 1)
    pts, walk = [], []
    for tid in range(2):
        per = []
        for cid in sched.chunks_of_thread(tid):
            b, e = sched.chunk_index_range(cid)
            for i in range(b, e):
                for j in range(4):
                    per.append(("R0", (i, j)))
                    per.append(("R1", (i, j)))
        walk.append(per)
    for tid in range(2):
        pts = [IterationPoint(nm, iv, priority=2 if nm == "R0" else 1)
               for nm, iv in walk[tid]]
        rng = random.Random(tid)
        shuf = pts[:]
        rng.shuffle(shuf)
        ivs = np.array([p.ivs for p in shuf])
        prios = np.array([p.priority for p in shuf])
        idx = interleaved_argsort(ivs, prios, sched)
        assert [(shuf[i].name, shuf[i].ivs) for i in idx] == walk[tid]


def test_mixed_depth_prefix_points():
    """A shallower ref sorts against deeper ones via common ivs + priority."""
    sched = _sched(trip=8, cs=4, T=2)
    # C0 at (i,j) [priority 3] precedes A0/B0 at (i,j,k) [2,1]
    pts = [
        IterationPoint("A0", (0, 1, 0), priority=2),
        IterationPoint("C0", (0, 1), priority=3),
        IterationPoint("B0", (0, 1, 0), priority=1),
        IterationPoint("C0", (0, 2), priority=3),
        IterationPoint("A0", (0, 1, 1), priority=2),
    ]
    want = sorted(pts, key=functools.cmp_to_key(
        lambda a, b: compare(a, b, sched)))
    ivs = np.full((len(pts), 3), 0, np.int64)
    lens = np.array([len(p.ivs) for p in pts])
    for i, p in enumerate(pts):
        ivs[i, : len(p.ivs)] = p.ivs
    idx = interleaved_argsort(
        ivs, np.array([p.priority for p in pts]), sched, lengths=lens)
    got = [pts[i] for i in idx]
    assert [(p.name, p.ivs) for p in got] == [(p.name, p.ivs) for p in want]
    # and the expected program order explicitly:
    assert [p.name for p in want] == ["C0", "A0", "B0", "A0", "C0"]


def test_iv_bitmap_packing_and_truncation():
    ivs = np.array([[1, 2, 3], [1, 2, 4]])
    bm = iv_bitmap(ivs)
    assert bm[0] == (1 << 2 * HASH_IV_BITS) | (2 << HASH_IV_BITS) | 3
    assert bm[0] != bm[1]
    # 4th iv does not contribute (3-slot truncation, iteration.rs:202-208)
    a = iv_bitmap(np.array([[1, 2, 3, 7]]))
    b = iv_bitmap(np.array([[1, 2, 3, 9]]))
    assert a[0] == b[0]


def test_point_hash_and_dedup():
    names = np.array([0, 0, 1, 0])
    ivs = np.array([[1, 2], [1, 2], [1, 2], [3, 4]])
    h = point_hash(names, ivs)
    assert h[0] == h[1] and h[0] != h[2]  # same point; name distinguishes
    keep = dedup(names, ivs)
    assert keep.tolist() == [0, 2, 3]
    # equality uses FULL ivs (no 3-slot truncation, iteration.rs:137-149)
    names4 = np.array([0, 0])
    ivs4 = np.array([[1, 2, 3, 7], [1, 2, 3, 9]])
    assert dedup(names4, ivs4).tolist() == [0, 1]
    assert point_hash(names4, ivs4)[0] == point_hash(names4, ivs4)[1]


def test_decompose_matches_schedule():
    sched = _sched(trip=64, cs=4, T=4)
    for v in range(0, 64, 7):
        p = IterationPoint("X", (v, 0))
        cid, tid, pos = p.decompose(sched)
        assert cid == sched.static_chunk_id(v)
        assert tid == sched.static_tid(v)
        assert pos == sched.static_thread_local_pos(v)
        assert sched.chunk_owner(sched.start_chunk_of(v)) == tid
