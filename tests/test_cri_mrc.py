"""Differential tests: production CRI/MRC (vectorized) vs the literal oracle."""

import math
import random

import numpy as np
import pytest

from pluss import cri, mrc
from pluss.config import DEFAULT, SamplerConfig
from tests import oracle


def rand_noshare(rng, nkeys=8, with_cold=True):
    h = {}
    if with_cold:
        h[-1] = float(rng.randint(0, 50))
    for _ in range(nkeys):
        h[1 << rng.randint(0, 14)] = float(rng.randint(1, 10_000))
    return h


def rand_share(rng, nkeys=4):
    return {3: {rng.randint(2, 100_000): float(rng.randint(1, 5_000))
                for _ in range(nkeys)}}


@pytest.mark.parametrize("seed", range(6))
def test_distribute_matches_oracle(seed):
    rng = random.Random(seed)
    T = rng.choice([2, 4, 8])
    noshare = [rand_noshare(rng) for _ in range(T)]
    share = [rand_share(rng) for _ in range(T)]
    got = cri.distribute(noshare, share, T)
    want = oracle.cri_distribute(
        [dict(h) for h in noshare], [dict(h) for h in share], T
    )
    assert set(got) == set(want)
    for k in want:
        assert math.isclose(got[k], want[k], rel_tol=1e-9, abs_tol=1e-12), k


def test_distribute_thread_cnt_1_passthrough():
    noshare = [{4: 10.0, -1: 2.0}]
    share = [{3: {100: 5.0}}]
    got = cri.distribute(noshare, share, 1)
    assert got == {4: 10.0, -1: 2.0, 64: 5.0}


def test_nbd_dilate_point_mass_cutoff():
    keys, pmf = cri.nbd_dilate(4, 3000)
    assert list(keys) == [12000] and list(pmf) == [1.0]
    keys, pmf = cri.nbd_dilate(4, 512)
    assert keys[0] == 512
    assert pmf.sum() > 0.9999
    # reference stops at the crossing term: dropping the last goes below cut
    assert pmf[:-1].sum() <= 0.9999


def test_racetrack_bins_small_ri():
    # ri < 2: loop body never runs; residual lands in bin 0 -> key int(2^-1)=0
    assert cri.racetrack_bins(1, 3.0) == [(0, 1.0)]
    # ri = 4, n = 3: bins 1..2, last overwritten by residual
    bins = dict(cri.racetrack_bins(4, 3.0))
    assert set(bins) == {1, 2}
    assert math.isclose(bins[1], 0.75**3 - 0.5**3)
    assert math.isclose(bins[2], 1 - 0.75**3)


@pytest.mark.parametrize("seed", range(8))
def test_aet_mrc_matches_oracle(seed):
    rng = random.Random(100 + seed)
    rihist = {}
    rihist[-1] = float(rng.randint(0, 100))
    for _ in range(rng.randint(1, 12)):
        rihist[rng.randint(1, 3000)] = float(rng.randint(1, 10_000))
    got = mrc.aet_mrc(rihist, DEFAULT)
    want = oracle.aet_mrc(rihist, DEFAULT.aet_cache_entries)
    assert len(got) == len(want)
    for c in range(len(got)):
        assert math.isclose(got[c], want[c], rel_tol=1e-9, abs_tol=1e-12), c


def test_aet_cache_entry_cap():
    cfg = SamplerConfig(cache_kb=1)  # 128 doubles
    rihist = {1: 1.0, 100000: 1.0}
    out = mrc.aet_mrc(rihist, cfg)
    assert len(out) == cfg.aet_cache_entries + 1


def test_dedup_lines_match_oracle():
    rng = random.Random(7)
    rihist = {-1: 5.0, 2: 100.0, 64: 500.0, 1024: 50.0}
    got_mrc = mrc.aet_mrc(rihist, DEFAULT)
    want_lines = oracle.mrc_dedup_lines({c: got_mrc[c] for c in range(len(got_mrc))})
    assert mrc.dedup_lines(got_mrc) == want_lines


def test_l2_error():
    a = np.array([1.0, 0.5, 0.25])
    assert mrc.l2_error(a, a) == 0.0
    assert mrc.l2_error(a, np.zeros(3)) > 0


def test_north_star_mrc_vs_native_gemm128():
    """BASELINE.json acceptance: reproduce the C++ GEMM-128 miss-ratio curve
    within 1% L2 error (the full engine -> CRI -> AET pipeline against the
    native C++ runtime's own pipeline)."""
    from pluss import engine, native
    from pluss.models import gemm

    if not native.available(autobuild=True):
        pytest.skip("native toolchain unavailable")
    res = engine.run(gemm(128))
    ri = cri.distribute(res.noshare_list(), res.share_list(),
                        DEFAULT.thread_num)
    ours = mrc.aet_mrc(ri)
    theirs = native.run(gemm(128)).mrc()
    assert len(ours) == len(theirs)
    err = mrc.l2_error(ours, theirs)
    assert err < 0.01, f"MRC L2 error {err:.2e} vs north-star bar 1%"
