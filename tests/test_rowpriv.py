"""Row-private groups (pluss.rowpriv): closed-form histograms vs the brute
single-iteration oracle, eligibility gates, and engine-level equality."""

import numpy as np
import pytest

from pluss import engine, rowpriv
from pluss.config import DEFAULT, SamplerConfig
from pluss.models import syrk_triangular, trmm
from pluss.sched import ChunkSchedule
from pluss.spec import Loop, LoopNestSpec, Ref, flatten_nest


def refs_of(spec, arr):
    return [fr for fr in flatten_nest(spec.nests[0]) if fr.ref.array == arr]


def sched_of(spec, cfg):
    n = spec.nests[0]
    return ChunkSchedule(cfg.chunk_size, n.trip, n.start, n.step,
                         cfg.thread_num)


@pytest.mark.parametrize("n,cls", [(16, 8), (16, 64), (24, 16), (13, 8)])
def test_group_hist_matches_brute_every_g(n, cls):
    spec = syrk_triangular(n)
    cfg = SamplerConfig(cls=cls)
    frs = refs_of(spec, "C")
    assert rowpriv.eligible(spec, 0, frs) is None
    sched = sched_of(spec, cfg)
    hg = rowpriv.group_hist(frs, cfg, sched, n)
    if (cls // cfg.ds) * cfg.ds != cls or (n * cfg.ds) % cls:
        assert hg is None  # misaligned rows: must refuse, not approximate
        return
    assert hg is not None
    for g in range(n):   # EVERY iteration, not just the plan-time samples
        np.testing.assert_array_equal(
            hg[g], rowpriv.brute_iteration_hist(frs, cfg, g), err_msg=str(g))


def test_syrk_tri_c_qualifies_a_does_not():
    spec = syrk_triangular(16)
    assert rowpriv.eligible(spec, 0, refs_of(spec, "C")) is None
    assert rowpriv.eligible(spec, 0, refs_of(spec, "A")) is not None


def test_misaligned_rows_refused():
    # n=13, cls=64: row stride 13*8=104 bytes is not line-aligned
    spec = syrk_triangular(13)
    cfg = SamplerConfig(cls=64)
    frs = refs_of(spec, "C")
    assert rowpriv.group_hist(frs, cfg, sched_of(spec, cfg), 13) is None


def test_plan_excludes_rowpriv_refs(monkeypatch, request):
    # sweepgroup disabled: isolate rowpriv's exclusions (C refs only)
    monkeypatch.setenv("PLUSS_NO_SWEEPGROUP", "1")
    engine.compiled.cache_clear()
    request.addfinalizer(engine.compiled.cache_clear)
    pl = engine.plan(syrk_triangular(16), SamplerConfig(cls=8))
    np_ = pl.nests[0]
    assert np_.rpg_hist is not None
    assert sorted(fr.ref.name for fr in np_.refs) == ["A0", "A1"]
    monkeypatch.delenv("PLUSS_NO_SWEEPGROUP")
    engine.compiled.cache_clear()
    assert np_.rpg_hist.shape[0] == DEFAULT.thread_num
    # the excluded refs' events (reuses + colds) are all in the table:
    # the grand total must equal C's stream size (every access is either a
    # cold or a reuse — C lines are private, nothing resolves elsewhere)
    n = 16
    expect = sum((2 + 2 * n) * (g + 1) for g in range(n))
    assert int(np_.rpg_hist.sum()) == expect


@pytest.mark.parametrize("model,n,cls", [
    ("syrk_tri", 16, 8), ("syrk_tri", 12, 64), ("trmm", 12, 8),
    ("symm", 12, 8), ("covariance", 12, 8),
])
def test_run_equal_with_and_without_rowpriv(model, n, cls, monkeypatch):
    from pluss.models import REGISTRY

    spec = REGISTRY[model](n)
    cfg = SamplerConfig(cls=cls)
    a = engine.run(spec, cfg)
    monkeypatch.setenv("PLUSS_NO_ROWPRIV", "1")
    engine.compiled.cache_clear()
    engine._plan_cached.cache_clear()
    b = engine.run(spec, cfg)
    monkeypatch.delenv("PLUSS_NO_ROWPRIV")
    engine.compiled.cache_clear()
    engine._plan_cached.cache_clear()
    assert a.max_iteration_count == b.max_iteration_count
    np.testing.assert_array_equal(a.noshare_dense, b.noshare_dense)
    assert a.share_list() == b.share_list()


def test_rowpriv_with_dynamic_assignment_and_resume():
    # the [T, NW] table is built from the owned matrix, so permuted chunk
    # maps and resume skips must be encoded exactly
    spec = syrk_triangular(16)
    cfg = SamplerConfig(cls=8)
    from tests.oracle import OracleSampler

    asg = tuple(np.random.default_rng(5).integers(0, 4, 4).tolist())
    a = engine.run(spec, cfg, assignment=(asg,))
    o = OracleSampler(spec, cfg).run(assignment=(asg,))
    assert a.noshare_list() == o.noshare
    b = engine.run(spec, cfg, start_point=8)
    o2 = OracleSampler(spec, cfg).run(start_point=8)
    assert b.noshare_list() == o2.noshare


def test_sliced_runner_carries_rowpriv_tables():
    spec = syrk_triangular(16)
    cfg = SamplerConfig(cls=8)
    a = engine.run(spec, cfg)
    b = engine.run_sliced(spec, cfg, max_dispatch_entries=1)
    np.testing.assert_array_equal(a.noshare_dense, b.noshare_dense)
    assert a.share_list() == b.share_list()


def test_all_rowpriv_nest_pure_table():
    # a nest whose ONLY array is row-private: windows become pure table
    # adds (the empty-sort-refs branch)
    n = 16
    spec = LoopNestSpec(
        name="rowwalk",
        arrays=(("X", n * n),),
        nests=(Loop(trip=n, body=(
            Loop(trip=n, bound_coef=(1, 1), body=(
                Ref("X0", "X", addr_terms=((0, n), (1, 1))),
                Ref("X1", "X", addr_terms=((0, n), (1, 1))),
            )),
        )),),
    )
    cfg = SamplerConfig(cls=8)
    pl = engine.plan(spec, cfg)
    assert pl.nests[0].rpg_hist is not None and not pl.nests[0].refs
    from tests.oracle import OracleSampler

    res = engine.run(spec, cfg)
    o = OracleSampler(spec, cfg).run()
    assert res.noshare_list() == o.noshare
    assert res.max_iteration_count == o.max_iteration_count
