"""PolyBench solver/medley families (pluss.models.solvers) vs the oracle.

Each family pins a distinct engine corner (see the module docstring of
:mod:`pluss.models.solvers`): trisolv (bounded loop + rectangular tail),
durbin (negative address coefficients, sibling bounded loops), gramschmidt
(rectangular loops inside a bounded varying-start loop), floyd_warshall
(parallel-invariant access pattern on a single array).  The reference has
no such samplers (its one workload is rectangular GEMM,
``/root/reference/c_lib/test/gemm.ppcg_omp.c:90-96``) — this is capability
surface, tested the way SURVEY.md §4 prescribes: parallel semantics must
equal sequential enumeration (the oracle).
"""

import pytest

from pluss import engine
from pluss.config import SamplerConfig
from pluss.models import durbin, floyd_warshall, gramschmidt, trisolv

from tests.oracle import OracleSampler
from tests.oracle import assert_result_matches_oracle as assert_matches_oracle

FAMILIES = {
    "trisolv": trisolv,
    "durbin": durbin,
    "gramschmidt": gramschmidt,
    "floyd_warshall": floyd_warshall,
}


@pytest.mark.parametrize("name", sorted(FAMILIES))
@pytest.mark.parametrize(
    "cfg", [SamplerConfig(cls=8), SamplerConfig(),
            SamplerConfig(thread_num=3, chunk_size=5, cls=16)],
    ids=["cls8", "default", "t3c5cls16"],
)
def test_engine_matches_oracle(name, cfg):
    spec = FAMILIES[name](12)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


@pytest.mark.parametrize("name", sorted(FAMILIES))
def test_odd_size_matches_oracle(name):
    # trip 13 (durbin: parallel trip 12): partial chunks + idle threads
    spec = FAMILIES[name](13)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


@pytest.mark.parametrize("name", ["durbin", "gramschmidt"])
def test_windowed_scan_matches_oracle(name):
    # tiny windows force multi-window scans (durbin: with the clock table)
    spec = FAMILIES[name](10)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg,
                          engine.run(spec, cfg, window_accesses=1))


def test_durbin_negative_coef_addresses_stay_in_array():
    # the backwards walk r[k-i-1] must never leave r's line range: every
    # emitted line id of array r lies inside [base, base+lines)
    spec = durbin(9)
    cfg = SamplerConfig(cls=8)
    o = OracleSampler(spec, cfg)
    o.run()
    n_lines = spec.line_counts(cfg)[spec.array_index("r")]
    for t in range(cfg.thread_num):
        for line in o.lat[t]["r"]:
            assert 0 <= line < n_lines


def test_trisolv_total_count_closed_form():
    # per i: 2 head + 4*i loop + 3 tail accesses -> sum = 5n + 4*n(n-1)/2
    n = 11
    res = engine.run(trisolv(n), SamplerConfig())
    assert res.max_iteration_count == 5 * n + 2 * n * (n - 1)


@pytest.mark.parametrize("name,n", [("floyd_warshall", 12), ("trisolv", 16)])
def test_shard_matches_engine(name, n):
    from pluss.parallel.shard import default_mesh, shard_run

    spec = FAMILIES[name](n)
    cfg = SamplerConfig(cls=8)
    want = engine.run(spec, cfg)
    got = shard_run(spec, cfg, mesh=default_mesh(4))
    assert got.max_iteration_count == want.max_iteration_count
    assert (got.noshare_dense == want.noshare_dense).all()
    assert got.share_list() == want.share_list()


def test_durbin_start_point_resume_matches_oracle():
    # setStartPoint capability on a bounded nest whose parallel loop
    # starts at 1 (start_point is an iteration VALUE, like the C++
    # setStartPoint's Iteration argument)
    spec = durbin(10)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg,
                          engine.run(spec, cfg, start_point=5),
                          start_point=5)
