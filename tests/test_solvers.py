"""PolyBench solver/medley families (pluss.models.solvers) vs the oracle.

Each family pins a distinct engine corner (see the module docstring of
:mod:`pluss.models.solvers`): trisolv (bounded loop + rectangular tail),
durbin (negative address coefficients, sibling bounded loops), gramschmidt
(rectangular loops inside a bounded varying-start loop), floyd_warshall
(parallel-invariant access pattern on a single array).  The reference has
no such samplers (its one workload is rectangular GEMM,
``/root/reference/c_lib/test/gemm.ppcg_omp.c:90-96``) — this is capability
surface, tested the way SURVEY.md §4 prescribes: parallel semantics must
equal sequential enumeration (the oracle).
"""

import pytest

from pluss import engine
from pluss.config import SamplerConfig
from pluss.models import (cholesky, durbin, floyd_warshall, gramschmidt,
                          lu, trisolv)

from tests.oracle import OracleSampler
from tests.oracle import assert_result_matches_oracle as assert_matches_oracle

FAMILIES = {
    "trisolv": trisolv,
    "durbin": durbin,
    "gramschmidt": gramschmidt,
    "floyd_warshall": floyd_warshall,
}


@pytest.mark.parametrize("name", sorted(FAMILIES))
@pytest.mark.parametrize(
    "cfg", [SamplerConfig(cls=8), SamplerConfig(),
            SamplerConfig(thread_num=3, chunk_size=5, cls=16)],
    ids=["cls8", "default", "t3c5cls16"],
)
def test_engine_matches_oracle(name, cfg):
    spec = FAMILIES[name](12)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


@pytest.mark.parametrize("name", sorted(FAMILIES))
def test_odd_size_matches_oracle(name):
    # trip 13 (durbin: parallel trip 12): partial chunks + idle threads
    spec = FAMILIES[name](13)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


@pytest.mark.parametrize("name", ["durbin", "gramschmidt"])
def test_windowed_scan_matches_oracle(name):
    # tiny windows force multi-window scans (durbin: with the clock table)
    spec = FAMILIES[name](10)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg,
                          engine.run(spec, cfg, window_accesses=1))


def test_durbin_negative_coef_addresses_stay_in_array():
    # the backwards walk r[k-i-1] must never leave r's line range: every
    # emitted line id of array r lies inside [base, base+lines)
    spec = durbin(9)
    cfg = SamplerConfig(cls=8)
    o = OracleSampler(spec, cfg)
    o.run()
    n_lines = spec.line_counts(cfg)[spec.array_index("r")]
    for t in range(cfg.thread_num):
        for line in o.lat[t]["r"]:
            assert 0 <= line < n_lines


def test_trisolv_total_count_closed_form():
    # per i: 2 head + 4*i loop + 3 tail accesses -> sum = 5n + 4*n(n-1)/2
    n = 11
    res = engine.run(trisolv(n), SamplerConfig())
    assert res.max_iteration_count == 5 * n + 2 * n * (n - 1)


@pytest.mark.parametrize("name,n", [("floyd_warshall", 12), ("trisolv", 16)])
def test_shard_matches_engine(name, n):
    from pluss.parallel.shard import default_mesh, shard_run

    spec = FAMILIES[name](n)
    cfg = SamplerConfig(cls=8)
    want = engine.run(spec, cfg)
    got = shard_run(spec, cfg, mesh=default_mesh(4))
    assert got.max_iteration_count == want.max_iteration_count
    assert (got.noshare_dense == want.noshare_dense).all()
    assert got.share_list() == want.share_list()


QUAD = {"cholesky": cholesky, "lu": lu}


@pytest.mark.parametrize("name", sorted(QUAD))
@pytest.mark.parametrize(
    "cfg", [SamplerConfig(cls=8), SamplerConfig(),
            SamplerConfig(thread_num=3, chunk_size=5, cls=16)],
    ids=["cls8", "default", "t3c5cls16"],
)
def test_quad_engine_matches_oracle(name, cfg):
    spec = QUAD[name](12)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


@pytest.mark.parametrize("name", sorted(QUAD))
def test_quad_odd_size_matches_oracle(name):
    spec = QUAD[name](13)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


@pytest.mark.parametrize("name", sorted(QUAD))
def test_quad_windowed_scan_matches_oracle(name):
    spec = QUAD[name](10)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg,
                          engine.run(spec, cfg, window_accesses=1))


@pytest.mark.parametrize("name", sorted(QUAD))
def test_quad_seq_and_resume_match_oracle(name):
    spec = QUAD[name](10)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg, backend="seq"))
    assert_matches_oracle(spec, cfg, engine.run(spec, cfg, start_point=5),
                          start_point=5)


@pytest.mark.parametrize("name", sorted(QUAD))
def test_quad_shard_matches_engine(name):
    from pluss.parallel.shard import default_mesh, shard_run

    spec = QUAD[name](12)
    cfg = SamplerConfig(cls=8)
    want = engine.run(spec, cfg)
    got = shard_run(spec, cfg, mesh=default_mesh(4))
    assert got.max_iteration_count == want.max_iteration_count
    assert (got.noshare_dense == want.noshare_dense).all()
    assert got.share_list() == want.share_list()


def _brute_positions(nest):
    """Program-order positions of one parallel iteration — the independent
    check of flatten_nest_quad's degree-2 closed forms."""
    from pluss.spec import Ref

    out = {}

    def trip_of(loop, g, idxs):
        if loop.bound_coef is None:
            return loop.trip
        a, b = loop.bound_coef
        ref = g if loop.bound_level == 0 else idxs[loop.bound_level - 1]
        return a + b * ref

    def walk(item, g, idxs, pos):
        if isinstance(item, Ref):
            out[(item.name, tuple(idxs))] = pos
            return pos + 1
        for t in range(trip_of(item, g, idxs)):
            for b in item.body:
                pos = walk(b, g, idxs + [t], pos)
        return pos

    def run(g):
        out.clear()
        pos = 0
        for b in nest.body:
            pos = walk(b, g, [], pos)
        return dict(out)

    return run


@pytest.mark.parametrize("name", sorted(QUAD))
def test_quad_flatten_positions_exact(name):
    from pluss.spec import flatten_nest, nest_is_quad

    spec = QUAD[name](9)
    nest = spec.nests[0]
    assert nest_is_quad(nest)
    frs = flatten_nest(nest)
    brute = _brute_positions(nest)
    tri = lambda x: x * (x - 1) // 2
    for g in range(nest.trip):
        want = brute(g)
        got = {}
        for fr in frs:
            def occs(l, idxs):
                if l == len(fr.trips):
                    pos = fr.offset + fr.offset_k * g \
                        + fr.offset_g2 * tri(g)
                    for lv in range(1, len(fr.trips)):
                        pos += idxs[lv - 1] * (
                            fr.pos_strides[lv] + fr.pos_strides_k[lv] * g)
                        if fr.pos_quads:
                            pos += fr.pos_quads[lv] * tri(idxs[lv - 1])
                    got[(fr.ref.name, tuple(idxs))] = pos
                    return
                t_eff = fr.trips[l]
                if fr.bounds and fr.bounds[l] is not None:
                    a, b = fr.bounds[l]
                    t_eff = a + b * g
                for lv, a, b, rl in fr.inner_bounds or ():
                    if lv == l:
                        t_eff = a + b * idxs[rl - 1]
                for t in range(t_eff):
                    occs(l + 1, idxs + [t])
            occs(1, [])
        assert got == want, (name, g)


def test_quad_iteration_sizes_exact():
    import numpy as np

    from pluss.spec import nest_iteration_sizes

    for build in (cholesky, lu):
        nest = build(11).nests[0]
        brute = _brute_positions(nest)
        want = [len(brute(g)) for g in range(nest.trip)]
        got = nest_iteration_sizes(nest, np.arange(nest.trip))
        assert got.tolist() == want, build.__name__


def test_quad_contract_rejections():
    from pluss.spec import Loop, Ref, flatten_nest

    r = lambda: Ref("R", "A", addr_terms=((0, 1),))
    # triply-triangular: a bounded loop inside a bounded-on-inner loop
    with pytest.raises(ValueError, match="must not contain bounded"):
        flatten_nest(Loop(trip=4, body=(
            Loop(trip=4, bound_coef=(0, 1), body=(
                Loop(trip=4, bound_coef=(0, 1), bound_level=1, body=(
                    Loop(trip=4, bound_coef=(0, 1), body=(r(),)),
                )),
            )),
        )))
    # bound_level must name an enclosing loop
    with pytest.raises(ValueError, match="enclosing"):
        flatten_nest(Loop(trip=4, body=(
            Loop(trip=4, bound_coef=(0, 1), bound_level=2, body=(r(),)),
        )))
    # the referenced level must have index == value (start=0, step=1)
    with pytest.raises(ValueError, match="index == value"):
        flatten_nest(Loop(trip=4, body=(
            Loop(trip=4, start=1, body=(
                Loop(trip=4, bound_coef=(0, 1), bound_level=1,
                     body=(r(),)),
            )),
        )))


def test_quad_native_matches_engine():
    from pluss import native
    from pluss.config import DEFAULT

    for build in (cholesky, lu):
        spec = build(12)
        want = engine.run(spec, DEFAULT)
        got = native.run(spec, DEFAULT)
        assert got.max_iteration_count == want.max_iteration_count
        assert got.noshare_list() == want.noshare_list()
        assert got.share_list() == want.share_list()


def test_cholesky_total_count_closed_form():
    # per i: sum_{j<i}(4j+3) + 4i + 2 = 2i^2 + 5i + 2
    n = 10
    res = engine.run(cholesky(n), SamplerConfig())
    want = sum(2 * i * i + 5 * i + 2 for i in range(n))
    assert res.max_iteration_count == want


def test_durbin_start_point_resume_matches_oracle():
    # setStartPoint capability on a bounded nest whose parallel loop
    # starts at 1 (start_point is an iteration VALUE, like the C++
    # setStartPoint's Iteration argument)
    spec = durbin(10)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg,
                          engine.run(spec, cfg, start_point=5),
                          start_point=5)


@pytest.mark.parametrize(
    "name,n",
    [("ludcmp", 10),
     # odd-trip composite rides tier-1 at n=10; 13 is the slow-tier rerun
     pytest.param("ludcmp", 13, marks=pytest.mark.slow),
     ("seidel2d", 8)])
def test_composite_families_match_oracle(name, n):
    """ludcmp: the integration stress case — a quad LU nest, a forward-
    substitution nest and a DESCENDING back-substitution nest share one
    LAT/clock state; seidel2d: a fully parallel-invariant time loop."""
    from pluss.models import REGISTRY

    spec = REGISTRY[name](n)
    for cfg in (SamplerConfig(cls=8),
                SamplerConfig(thread_num=3, chunk_size=5, cls=16)):
        assert_matches_oracle(spec, cfg, engine.run(spec, cfg))


@pytest.mark.parametrize("name,n", [("ludcmp", 10), ("seidel2d", 8)])
def test_composite_windowed_and_shard_match(name, n):
    from pluss.models import REGISTRY
    from pluss.parallel.shard import default_mesh, shard_run

    spec = REGISTRY[name](n)
    cfg = SamplerConfig(cls=8)
    assert_matches_oracle(spec, cfg,
                          engine.run(spec, cfg, window_accesses=1))
    want = engine.run(spec, cfg)
    got = shard_run(spec, cfg, mesh=default_mesh(4))
    assert got.max_iteration_count == want.max_iteration_count
    assert (got.noshare_dense == want.noshare_dense).all()
    assert got.share_list() == want.share_list()
